"""Dataset protocol + deterministic synthetic datasets.

All iterators yield **global** batches (the full cross-replica batch) as
numpy dicts ``{"x": [B, ...], "y": [B]}`` with constant shapes — the rule's
trainer shards the leading dim over the ``data`` mesh axis and jit requires
static shapes, so ragged final batches are dropped (the reference did the
same via ``file_batch_size`` bookkeeping; SURVEY.md §2.3, unverified).
"""

from __future__ import annotations

import hashlib
import sys
import threading
import time

import numpy as np


class DataReadError(RuntimeError):
    """A dataset read kept failing after the bounded retries — the typed
    terminal error loaders raise instead of leaking the first IOError
    (under supervision this is a restartable crash, and the message says
    which file and how many attempts)."""


def derive_seed(*parts) -> int:
    """Derive a 31-bit numpy seed from structured parts, stably.

    The one seed-derivation helper every dataset uses (ISSUE 10 satellite;
    replaces the scattered ``hash((seed, epoch)) % (2**31)`` idiom).  Keyed
    draws — ``derive_seed("augment", seed, epoch, batch_index)`` — make any
    batch recomputable in isolation, which mid-epoch cursor fast-forward
    depends on.  Built on sha256 of the ``repr`` of the parts, so the value
    is identical across processes, platforms and interpreter restarts
    (``hash`` of a str/bytes part would depend on ``PYTHONHASHSEED``), and
    distinct part *positions* never collide (parts are joined with an
    unambiguous separator, not concatenated).
    """
    text = "\x1f".join(repr(p) for p in parts)
    digest = hashlib.sha256(text.encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "little") % (2**31)


# -- data-plane hooks (telemetry + deterministic fault injection) -------------
# Datasets are constructed by models, far from the trainer's telemetry and
# fault plan — so the trainer publishes both through this module-level
# registration instead of threading them through every Dataset __init__.
# One process, one data plane: pool workers (separate spawned processes)
# intentionally see no hooks.

_HOOKS_LOCK = threading.Lock()
_DATA_TELEMETRY = None
_DATA_FAULT_PLAN = None
_READ_ORDINAL = 0
#: set → any in-progress ``data:stall`` injection returns (tests use this to
#: unwedge the loader thread before closing the Prefetcher)
_STALL_RELEASE = threading.Event()


def set_data_hooks(telemetry=None, fault_plan=None) -> None:
    """Install (or clear, with Nones) the process-wide data-plane hooks.

    ``telemetry`` receives a ``data.retries`` counter event per retried
    read; ``fault_plan`` enables the ``data:torn_read@i`` /
    ``data:stall@i`` sites inside :func:`read_with_retry`, where ``i`` is
    the process-global read ordinal (every ``read_with_retry`` call counts,
    in call order).  Also resets the read ordinal so fault indices are
    deterministic per (re)installation.
    """
    global _DATA_TELEMETRY, _DATA_FAULT_PLAN, _READ_ORDINAL
    with _HOOKS_LOCK:
        _DATA_TELEMETRY = telemetry
        _DATA_FAULT_PLAN = fault_plan
        _READ_ORDINAL = 0
        _STALL_RELEASE.clear()


def release_data_stalls() -> None:
    """Unblock any thread wedged in an injected ``data:stall`` (tests)."""
    _STALL_RELEASE.set()


def _next_read_ordinal() -> int:
    global _READ_ORDINAL
    with _HOOKS_LOCK:
        i = _READ_ORDINAL
        _READ_ORDINAL += 1
        return i


def read_with_retry(fn, what: str, retries: int = 4,
                    backoff_s: float = 0.05, sleep=time.sleep):
    """Run a read callable with bounded exponential-backoff retries.

    ISSUE 5 satellite: shared-filesystem reads (NFS/GCS-fuse shards) fail
    transiently all the time — a single EIO must cost one short retry, not
    the whole training attempt.  ``OSError`` (which includes
    ``FileNotFoundError`` from eventually-consistent mounts) and numpy's
    ``ValueError`` for a torn partial read are retried ``retries`` times
    with doubling ``backoff_s``; exhaustion raises the typed
    :class:`DataReadError` carrying the last cause.

    ISSUE 10 satellite: every retry lands in the ``data.retries`` telemetry
    counter (when hooks are installed — retries used to be stderr-only,
    invisible to rank-0 aggregation), and the ``data:torn_read@i`` /
    ``data:stall@i`` fault sites fire here, making both paths
    deterministically testable.
    """
    retries = max(1, int(retries))
    plan, tel = _DATA_FAULT_PLAN, _DATA_TELEMETRY
    ordinal = _next_read_ordinal() if plan is not None else -1
    last: Exception | None = None
    for attempt in range(1, retries + 1):
        injected: Exception | None = None
        if plan is not None:
            action = plan.fire("data", ordinal)
            if action == "stall":
                # a wedged read (dead NFS mount): produce nothing — the
                # consumer-side witness is the Prefetcher's stall_timeout
                while not _STALL_RELEASE.wait(0.05):
                    pass
                from theanompi_tpu.resilience.faults import FaultInjected

                raise FaultInjected(f"injected data stall reading {what}")
            if action == "torn_read":
                # the torn-partial-read shape numpy raises for a file that
                # changed size underneath it — retried like the real thing
                injected = ValueError(
                    f"injected torn read of {what} (fault plan)")
        try:
            if injected is not None:
                raise injected
            return fn()
        except (OSError, ValueError) as e:
            last = e
            if tel is not None:
                tel.count("data.retries", emit=True, what=what)
            if attempt < retries:
                print(f"data: read of {what} failed "
                      f"(attempt {attempt}/{retries}): {e}; retrying",
                      file=sys.stderr, flush=True)
                sleep(backoff_s * (2 ** (attempt - 1)))
    raise DataReadError(
        f"could not read {what} after {retries} attempts: {last}"
    ) from last


class Dataset:
    """Duck-typed dataset: n_train/n_val counts + batch iterators.

    Iterator-state contract (ISSUE 10): every dataset is a checkpointable,
    deterministic component.  ``train_batches`` accepts ``start_batch`` —
    the global batch cursor to fast-forward to — and MUST reproduce, from
    cursor ``k`` onward, exactly the batches an uninterrupted epoch-``epoch``
    iteration would have yielded from position ``k`` (bit-equal, including
    augmentation noise).  That requires all randomness to be keyed on
    ``derive_seed(..., epoch, position)``, never drawn from a stream whose
    phase depends on how many batches were already produced.

    ``state()``/``set_state()`` carry whatever position the (epoch, cursor)
    pair the trainer checkpoints does NOT determine — per-source window
    cursors, mixture weights (see ``stream.py``).  Datasets whose iteration
    is a pure function of (epoch, cursor, seed) are stateless here: the
    defaults return/accept ``{}``.  The dict must be JSON-serializable and
    device-count-independent (it rides in the checkpoint manifest and must
    survive an elastic mesh8→4 resume unchanged).
    """

    n_train: int
    n_val: int
    sample_shape: tuple
    n_classes: int

    def n_train_batches(self, batch_size: int) -> int:
        return self.n_train // batch_size

    def n_val_batches(self, batch_size: int) -> int:
        return self.n_val // batch_size

    def train_batches(self, batch_size: int, epoch: int, seed: int = 0,
                      start_batch: int = 0):
        raise NotImplementedError

    def val_batches(self, batch_size: int):
        raise NotImplementedError

    def state(self) -> dict:
        """Checkpointable iterator state beyond the (epoch, cursor) pair."""
        return {}

    def set_state(self, state: dict) -> None:
        """Restore :meth:`state` output (no-op for stateless datasets)."""

    def cleanup(self) -> None:
        pass


class ArrayDataset(Dataset):
    """In-memory arrays with per-epoch shuffling and optional augmentation."""

    def __init__(self, x_train, y_train, x_val, y_val, n_classes,
                 augment_fn=None):
        self.x_train, self.y_train = x_train, y_train
        self.x_val, self.y_val = x_val, y_val
        self.n_train, self.n_val = len(x_train), len(x_val)
        self.sample_shape = tuple(x_train.shape[1:])
        self.n_classes = n_classes
        self.augment_fn = augment_fn

    def epoch_order(self, epoch, seed=0):
        """The epoch's sample permutation — a pure function of (seed,
        epoch), so a cursor fast-forward re-derives it without replay."""
        rng = np.random.RandomState(derive_seed("shuffle", seed, epoch))
        return rng.permutation(self.n_train)

    def train_batches(self, batch_size, epoch, seed=0, start_batch=0):
        order = self.epoch_order(epoch, seed)
        for i in range(int(start_batch), self.n_train_batches(batch_size)):
            idx = order[i * batch_size : (i + 1) * batch_size]
            x = self.x_train[idx]
            if self.augment_fn is not None:
                # per-batch derived rng (NOT the permutation's stream):
                # batch i's augmentation is recomputable in isolation, so
                # resuming at cursor k reproduces batch k bit-equal
                rng = np.random.RandomState(
                    derive_seed("augment", seed, epoch, i))
                x = self.augment_fn(x, rng)
            yield {"x": x, "y": self.y_train[idx]}

    def val_batches(self, batch_size):
        for i in range(self.n_val_batches(batch_size)):
            sl = slice(i * batch_size, (i + 1) * batch_size)
            yield {"x": self.x_val[sl], "y": self.y_val[sl]}


def _class_structured(n, shape, n_classes, seed, noise=0.3, means_seed=0):
    """Learnable synthetic data: one Gaussian blob per class.

    Gives tests/benchmarks something a model can actually fit, so "loss
    decreases" is a meaningful assertion — stand-in for the real datasets in
    this zero-egress environment (real data plugs in via the same classes).
    ``means_seed`` fixes the class means independently of the sample draw so
    train and val splits share one distribution.
    """
    dim = int(np.prod(shape))
    means = np.random.RandomState(means_seed).randn(n_classes, dim).astype(np.float32)
    rng = np.random.RandomState(seed)
    y = rng.randint(0, n_classes, size=n).astype(np.int32)
    x = means[y] + noise * rng.randn(n, dim).astype(np.float32)
    return x.reshape(n, *shape), y


class SyntheticDataset(ArrayDataset):
    def __init__(self, n_train=1024, n_val=256, sample_shape=(8, 8, 3),
                 n_classes=10, seed=0, noise=0.3):
        xt, yt = _class_structured(
            n_train, sample_shape, n_classes, seed, noise, means_seed=seed
        )
        xv, yv = _class_structured(
            n_val, sample_shape, n_classes, seed + 1, noise, means_seed=seed
        )
        super().__init__(xt, yt, xv, yv, n_classes)


class SyntheticSequenceDataset(Dataset):
    """Synthetic token streams for LM models (PTB stand-in).

    Sequences follow a fixed random bigram table so there is real structure
    to learn (perplexity can drop well below vocab size).
    """

    def __init__(self, n_train=512, n_val=128, seq_len=32, vocab=64, seed=0,
                 dense_vocab_limit=4096):
        rng = np.random.RandomState(seed)
        self.vocab = vocab
        self.n_classes = vocab
        self.seq_len = seq_len
        self.sample_shape = (seq_len,)
        if vocab <= dense_vocab_limit:
            # peaked bigram transition table
            logits = rng.randn(vocab, vocab) * 2.0
            probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
            self._probs = probs

            def gen(n, r):
                seqs = np.zeros((n, seq_len + 1), np.int32)
                seqs[:, 0] = r.randint(0, vocab, n)
                for t in range(seq_len):
                    cur = seqs[:, t]
                    u = r.rand(n, 1)
                    cdf = probs[cur].cumsum(1)
                    # clamp: float cumsum can top out below 1.0, and a draw
                    # above it would index one past the last class
                    seqs[:, t + 1] = np.minimum((u > cdf).sum(1), vocab - 1)
                return seqs
        else:
            # Large-vocab (32k-class LM benches): the dense table is O(V^2)
            # — 8 GB at V=32k — so transitions go procedural-sparse instead:
            # every token has S successors at (a*cur + c + j*j) % V, drawn
            # from ONE shared peaked categorical over j.  O(S) memory, still
            # bigram-learnable (entropy exp(H(w)) << V).
            s_succ = 32
            a = 2 * rng.randint(1, vocab // 2) + 1  # odd -> bijective map
            c = rng.randint(vocab)
            wl = np.sort(rng.randn(s_succ) * 2.0)[::-1]
            w = np.exp(wl) / np.exp(wl).sum()
            cdf = w.cumsum()

            def gen(n, r):
                seqs = np.zeros((n, seq_len + 1), np.int32)
                seqs[:, 0] = r.randint(0, vocab, n)
                j2 = np.arange(s_succ, dtype=np.int64) ** 2
                for t in range(seq_len):
                    cur = seqs[:, t].astype(np.int64)
                    # same clamp as the dense branch: cdf[-1] can be < 1.0
                    j = np.minimum((r.rand(n, 1) > cdf).sum(1), s_succ - 1)
                    seqs[:, t + 1] = (a * cur + c + j2[j]) % vocab
                return seqs

        self._train = gen(n_train, np.random.RandomState(seed + 1))
        self._val = gen(n_val, np.random.RandomState(seed + 2))
        self.n_train, self.n_val = n_train, n_val

    def train_batches(self, batch_size, epoch, seed=0, start_batch=0):
        rng = np.random.RandomState(derive_seed("shuffle", seed, epoch))
        order = rng.permutation(self.n_train)
        for i in range(int(start_batch), self.n_train // batch_size):
            idx = order[i * batch_size : (i + 1) * batch_size]
            s = self._train[idx]
            yield {"x": s[:, :-1], "y": s[:, 1:]}

    def val_batches(self, batch_size):
        for i in range(self.n_val // batch_size):
            s = self._val[i * batch_size : (i + 1) * batch_size]
            yield {"x": s[:, :-1], "y": s[:, 1:]}
