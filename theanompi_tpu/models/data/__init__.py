"""Data layer: dataset classes + the parallel-loading pipeline.

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/data/`` —
``imagenet.py`` (hickle ``.hkl`` shard lists, mean subtraction, crop+mirror),
``cifar10.py`` (in-memory), ``proc_load_mpi.py`` (spawned loader process
overlapping augmentation with GPU compute, the "para_load" protocol).
"""

from theanompi_tpu.models.data.base import (
    Dataset,
    SyntheticDataset,
    derive_seed,
    set_data_hooks,
)
from theanompi_tpu.models.data.cifar10 import Cifar10Data

__all__ = ["Dataset", "SyntheticDataset", "Cifar10Data", "derive_seed",
           "set_data_hooks"]
