"""ImageNet-style sharded dataset.

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/data/imagenet.py``
— preprocessed hickle ``.hkl`` batch files plus label ``.npy``s; per-epoch
shuffling of the shard file list; mean subtraction; random crop + mirror
augmentation; worker-sharded iteration; ``para_load`` overlap (here supplied
by :mod:`theanompi_tpu.models.data.prefetch`).

On-disk layout expected under ``data_path`` (or ``$IMAGENET_PATH``)::

    train/x_0000.npy  uint8 [N, S, S, 3]   (S = store_size, e.g. 256)
    train/y_0000.npy  int32 [N]
    val/x_0000.npy ...

``.hkl`` inputs from a reference-era preprocessing run can be converted with
:func:`convert_hkl_tree` (requires ``hickle``, which is optional).  In this
zero-egress image a deterministic synthetic stand-in (per-class pattern +
noise, generated shard-by-shard so memory stays bounded) exercises the
identical shard/augment/batch pipeline.
"""

from __future__ import annotations

import os

import numpy as np

from theanompi_tpu.models.data.base import (
    Dataset,
    derive_seed,
    read_with_retry,
)

# ImageNet channel means in [0,255] RGB (the reference subtracted a stored
# per-pixel mean image; per-channel is the modern equivalent)
MEAN_RGB = np.array([123.68, 116.78, 103.94], np.float32)
STD_RGB = np.array([58.39, 57.12, 57.38], np.float32)


def random_crop_mirror(x: np.ndarray, out: int, rng: np.random.RandomState):
    """Random spatial crop to ``out`` + horizontal mirror (train augment).

    The per-image gather runs in C when the native helper is available
    (:mod:`theanompi_tpu.native`); the numpy loop below is the reference
    implementation both paths are tested equal against."""
    from theanompi_tpu import native

    n, h, w, _ = x.shape
    ys = rng.randint(0, h - out + 1, n)
    xs = rng.randint(0, w - out + 1, n)
    flips = rng.rand(n) < 0.5
    fast = native.crop_mirror_batch(x, out, out, ys, xs, flips)
    if fast is not None:
        return fast
    res = np.empty((n, out, out, x.shape[3]), x.dtype)
    for i in range(n):
        img = x[i, ys[i] : ys[i] + out, xs[i] : xs[i] + out]
        res[i] = img[:, ::-1] if flips[i] else img
    return res


def center_crop(x: np.ndarray, out: int):
    h, w = x.shape[1:3]
    y0, x0 = (h - out) // 2, (w - out) // 2
    return x[:, y0 : y0 + out, x0 : x0 + out]


def normalize(x: np.ndarray) -> np.ndarray:
    """Host-side normalization (kept for tools/tests).

    The training path does NOT use this: batches leave the loader as uint8
    (4x fewer host→device bytes — the transfer is the input pipeline's
    scarce resource on TPU) and the model normalizes on device via
    ``Dataset.norm_stats``, where XLA fuses the cast+scale into the first
    conv's HLO.
    """
    return (x.astype(np.float32) - MEAN_RGB) / STD_RGB


def write_shards(dirpath: str, x: np.ndarray, y: np.ndarray, shard_size: int):
    """Write arrays as the shard layout above (test/converter helper)."""
    os.makedirs(dirpath, exist_ok=True)
    for s, start in enumerate(range(0, len(x), shard_size)):
        np.save(os.path.join(dirpath, f"x_{s:04d}.npy"), x[start : start + shard_size])
        np.save(os.path.join(dirpath, f"y_{s:04d}.npy"), y[start : start + shard_size])


def convert_hkl_tree(src: str, dst: str) -> None:
    """Convert a reference-era hickle shard tree to the ``.npy`` layout.

    Gated on the optional ``hickle`` dependency.  **Status honesty
    (VERDICT r4 #5):** hickle is NOT installed in this image and cannot be
    (no network), so this path has never run against a real ``.hkl`` tree
    here — the conversion loop itself is exercised only with a stubbed
    ``hickle`` module (``tests/test_data.py``), which validates the
    file ordering, the CHW→HWC transpose, and the uint8 output layout but
    not hickle's actual on-disk format.  Labels are not part of the tree
    (the reference kept them in separate ``.npy`` files already — pair the
    output with ``write_shards``-style ``y_*.npy`` files).
    """
    try:
        import hickle
    except ImportError as e:
        raise ImportError(
            "hickle is not installed; convert_hkl_tree needs it to read "
            ".hkl shards. Preprocess to .npy shards directly instead "
            "(see write_shards)."
        ) from e
    os.makedirs(dst, exist_ok=True)
    files = sorted(f for f in os.listdir(src) if f.endswith(".hkl"))
    for i, f in enumerate(files):
        arr = np.asarray(hickle.load(os.path.join(src, f)))
        if arr.shape[1] == 3:  # reference stored CHW; we store HWC
            arr = arr.transpose(0, 2, 3, 1)
        np.save(os.path.join(dst, f"x_{i:04d}.npy"), arr.astype(np.uint8))


class _ShardSet:
    """One split: a list of (x, y) shard files iterated in shuffled order."""

    def __init__(self, dirpath: str):
        xs = sorted(f for f in os.listdir(dirpath) if f.startswith("x_"))
        self.x_files = [os.path.join(dirpath, f) for f in xs]
        self.y_files = [
            os.path.join(dirpath, os.path.basename(p).replace("x_", "y_"))
            for p in self.x_files
        ]
        missing = [p for p in self.y_files if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(f"label shards missing: {missing[:3]}")
        # one pass over the headers serves both the count and the worker
        # ring's slot size (re-scanning thousands of shards would double
        # dataset construction time)
        lens = [
            int(read_with_retry(
                lambda p=p: np.load(p, mmap_mode="r").shape[0],
                what=p))
            for p in self.x_files
        ]
        self.lens = lens  # per-shard counts: cursor fast-forward arithmetic
        self.n = sum(lens)
        self.max_len = max(lens)

    def load(self, i: int):
        # bounded-retry reads (ISSUE 5 satellite): a transient EIO on a
        # shared mount costs a short backoff, not the training attempt;
        # exhaustion raises the typed DataReadError
        return (read_with_retry(lambda: np.load(self.x_files[i]),
                                what=self.x_files[i]),
                read_with_retry(lambda: np.load(self.y_files[i]),
                                what=self.y_files[i]))

    def spec(self, i: int):
        """Picklable shard handle for pool workers."""
        return ("files", self.x_files[i], self.y_files[i])

    def iter_shards(self, order):
        for i in order:
            yield self.load(i)


class _SyntheticShards:
    """Deterministic synthetic shards, generated lazily (bounded memory).

    Per-class signature: an 8×8×3 pattern seeded by the class id, tiled up to
    ``store_size`` — learnable structure without a 1000×S²×3 mean table.
    """

    def __init__(self, n: int, n_classes: int, store_size: int,
                 shard_size: int, seed: int):
        self.n = n
        self.n_classes = n_classes
        self.store_size = store_size
        self.shard_size = shard_size
        self.seed = seed
        self.n_shards = (n + shard_size - 1) // shard_size
        self.lens = [min(shard_size, n - i * shard_size)
                     for i in range(self.n_shards)]
        self._pattern_cache: dict[int, np.ndarray] = {}

    def _pattern(self, cls: int) -> np.ndarray:
        """The class's 8x8x3 signature (cached small; tiled per shard)."""
        p = self._pattern_cache.get(cls)
        if p is None:
            r = np.random.RandomState(1000003 + cls)
            p = r.randint(60, 196, size=(8, 8, 3)).astype(np.float32)
            self._pattern_cache[cls] = p
        return p

    def load(self, i: int):
        s = self.store_size
        reps = s // 8 + 1
        count = min(self.shard_size, self.n - i * self.shard_size)
        r = np.random.default_rng(self.seed * 7919 + int(i))
        y = r.integers(0, self.n_classes, count, dtype=np.int32)
        # vectorized: stack small patterns, tile to store size, one
        # fp32 noise draw for the whole shard (the per-image python
        # loop was the host bottleneck at bench batch sizes)
        pats = np.stack([self._pattern(int(c)) for c in y])
        pats = np.tile(pats, (1, reps, reps, 1))[:, :s, :s]
        noise = r.standard_normal((count, s, s, 3), dtype=np.float32)
        x = np.clip(pats + noise * 24.0, 0, 255).astype(np.uint8)
        return x, y

    def spec(self, i: int):
        """Picklable shard handle for pool workers."""
        return ("synth", self.n, self.n_classes, self.store_size,
                self.shard_size, self.seed, int(i))

    def iter_shards(self, order):
        for i in order:
            yield self.load(i)


def _load_from_spec(spec):
    if spec[0] == "files":
        # pool workers read the same flaky mounts the inline path does
        return (read_with_retry(lambda: np.load(spec[1]), what=spec[1]),
                read_with_retry(lambda: np.load(spec[2]), what=spec[2]))
    _, n, n_classes, store, shard, seed, i = spec
    return _SyntheticShards(n, n_classes, store, shard, seed).load(i)


class ImageNetData(Dataset):
    """Sharded ImageNet(-style) data with crop/mirror augmentation.

    Config keys: ``data_path`` (or ``$IMAGENET_PATH``), ``image_size`` (crop,
    default 224), ``store_size`` (stored resolution, default 256; synthetic
    only), ``n_classes`` (default 1000), and for the synthetic stand-in
    ``n_train``/``n_val``/``shard_size``.

    Batches are uint8; models normalize on device using ``norm_stats``
    (mean, inverse-std in [0,255] space) — see
    :meth:`theanompi_tpu.models.contract.SupervisedModel.loss_fn`.
    """

    #: on-device normalization constants: (mean, 1/std) in [0,255] RGB
    norm_stats = (MEAN_RGB, (1.0 / STD_RGB).astype(np.float32))

    def __init__(self, config: dict | None = None):
        config = config or {}
        self.image_size = config.get("image_size", 224)
        # host-side parallelism: one process cannot feed a v5e chip
        # (LOADER.json: single-thread load+crop ~1.2k img/s vs ~2.5k
        # demand), so train shards fan out over a fork pool.  0 = inline.
        self.loader_workers = int(config.get("loader_workers", 0))
        path = config.get("data_path") or os.environ.get("IMAGENET_PATH")
        if path and os.path.isdir(os.path.join(path, "train")):
            self.synthetic = False
            self._train = _ShardSet(os.path.join(path, "train"))
            self._val = _ShardSet(os.path.join(path, "val"))
            probe = read_with_retry(
                lambda: np.load(self._train.x_files[0], mmap_mode="r"),
                what=self._train.x_files[0])
            self.store_size = int(probe.shape[1])
            if "n_classes" in config:
                self.n_classes = config["n_classes"]
            else:
                # infer from BOTH splits: a sampled val set may lack the
                # highest class id, and an undersized head silently clips
                # labels in take_along_axis
                ys = [
                    read_with_retry(lambda p=p: np.load(p), what=p)
                    for p in (*self._train.y_files, *self._val.y_files)
                ]
                self.n_classes = int(max(y.max() for y in ys)) + 1
            self._train_shards = len(self._train.x_files)
            self._val_shards = len(self._val.x_files)
            self._max_shard = self._train.max_len
        else:
            self.synthetic = True
            self.store_size = config.get("store_size", max(self.image_size + 8, 64))
            self.n_classes = config.get("n_classes", 1000)
            shard = config.get("shard_size", 128)
            self._train = _SyntheticShards(
                config.get("n_train", 2048), self.n_classes, self.store_size,
                shard, seed=1,
            )
            self._val = _SyntheticShards(
                config.get("n_val", 512), self.n_classes, self.store_size,
                shard, seed=2,
            )
            self._train_shards = self._train.n_shards
            self._val_shards = self._val.n_shards
            self._max_shard = shard
        self.n_train = self._train.n
        self.n_val = self._val.n
        self.sample_shape = (self.image_size, self.image_size, 3)
        self._shm_pool = None

    def _pool(self):
        """The persistent worker ring, created lazily (spawn costs ~8 s on
        this image — paid once per dataset, reused every epoch)."""
        if self._shm_pool is None:
            from theanompi_tpu.models.data.shm_loader import ShmShardPool

            self._shm_pool = ShmShardPool(self.image_size, self._max_shard,
                                          self.loader_workers)
        return self._shm_pool

    def cleanup(self) -> None:
        if self._shm_pool is not None:
            self._shm_pool.close()
            self._shm_pool = None

    # -- iteration -----------------------------------------------------------
    def _augmented_shards(self, src, tagged, train: bool, epoch=0, seed=0):
        """-> iterator of per-shard (x, y), augmented for train.

        ``tagged`` is ``[(pos, shard_index), ...]`` — ``pos`` is the
        shard's position in the epoch's shard order, which keys that
        shard's augmentation seed (``derive_seed("augment", seed, epoch,
        pos)``), so any shard is recomputable in isolation for a cursor
        fast-forward.  ``loader_workers > 0`` (train only) fans shards over
        a spawn pool — load + C crop/mirror + shuffle all happen in the
        workers, the ring keeps shard order, and the worker performs the
        exact op sequence of the inline branch below on the same keyed
        seed, so the pool and inline paths produce ONE identical
        deterministic stream (locked by test).
        """
        if train and self.loader_workers > 0:
            tasks = [(src.spec(int(i)),
                      derive_seed("augment", seed, epoch, int(pos)))
                     for pos, i in tagged]
            yield from self._pool().run(tasks)
            return
        for pos, i in tagged:
            x, y = src.load(int(i))
            if train:
                rng = np.random.RandomState(
                    derive_seed("augment", seed, epoch, int(pos)))
                x = random_crop_mirror(x, self.image_size, rng)
                within = rng.permutation(len(x))
                x, y = x[within], y[within]
            else:
                x = center_crop(x, self.image_size)
            yield x, y

    def _batches(self, src, n_shards, batch_size, train: bool, epoch=0,
                 seed=0, start_batch=0):
        """Shuffled-shard iteration with a rolling remainder buffer, so exact
        constant-size batches are emitted across shard boundaries (the
        reference's file_batch_size/n_subb bookkeeping).

        ``start_batch`` fast-forwards by cursor arithmetic: whole shards
        that lie entirely before sample offset ``start_batch * batch_size``
        are never read or augmented (their keyed seeds make that sound),
        and the first surviving shard is trimmed by the residual — the
        yielded stream is the exact tail an uninterrupted epoch would have
        produced from that batch onward.
        """
        if train:
            order = np.random.RandomState(
                derive_seed("shards", seed, epoch)).permutation(n_shards)
        else:
            order = np.arange(n_shards)
        tagged = list(enumerate(order))
        skip = int(start_batch) * batch_size  # samples already consumed
        while tagged and skip >= src.lens[int(tagged[0][1])]:
            skip -= src.lens[int(tagged[0][1])]
            tagged = tagged[1:]
        buf_x: list[np.ndarray] = []
        buf_y: list[np.ndarray] = []
        have = 0
        for x, y in self._augmented_shards(src, tagged, train, epoch, seed):
            if skip:
                x, y = x[skip:], y[skip:]
                skip = 0
            buf_x.append(x)
            buf_y.append(y)
            have += len(x)
            while have >= batch_size:
                bx = np.concatenate(buf_x) if len(buf_x) > 1 else buf_x[0]
                by = np.concatenate(buf_y) if len(buf_y) > 1 else buf_y[0]
                # uint8 out: normalization happens on device (norm_stats)
                yield {"x": bx[:batch_size], "y": by[:batch_size]}
                buf_x, buf_y = [bx[batch_size:]], [by[batch_size:]]
                have -= batch_size
        # ragged tail dropped (constant shapes under jit)

    def train_batches(self, batch_size: int, epoch: int, seed: int = 0,
                      start_batch: int = 0):
        return self._batches(self._train, self._train_shards, batch_size,
                             train=True, epoch=epoch, seed=seed,
                             start_batch=start_batch)

    def val_batches(self, batch_size: int):
        return self._batches(self._val, self._val_shards, batch_size,
                             train=False)
