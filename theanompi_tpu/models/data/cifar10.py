"""CIFAR-10 dataset.

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/data/cifar10.py``
— in-memory CIFAR-10 with per-worker sharding, mean subtraction; crop/mirror
augmentation came from the shared loader utilities.

Real data loads from an ``.npz`` (keys ``x_train``/``y_train``/``x_test``/
``y_test``, uint8 NHWC) found via ``config['data_path']`` or
``$CIFAR10_PATH``; in this zero-egress image a class-structured synthetic
stand-in of the same shape is generated instead, so the full pipeline
(normalize → augment → shard → train) is exercised identically.
"""

from __future__ import annotations

import os

import numpy as np

from theanompi_tpu.models.data.base import ArrayDataset, _class_structured

MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


def pad_crop_mirror(x: np.ndarray, rng: np.random.RandomState, pad: int = 4):
    """Random pad-crop + horizontal mirror (the reference's augmentations).

    Host-side; runs inside the loader generator, which the para_load-
    equivalent prefetch thread (:mod:`theanompi_tpu.models.data.prefetch`)
    overlaps with device compute.  The reflect pad vectorizes in numpy;
    the per-image crop+mirror gather runs in C when available
    (:mod:`theanompi_tpu.native`), with the numpy loop as the tested
    reference fallback.
    """
    from theanompi_tpu import native

    n, h, w, c = x.shape
    padded = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect")
    ys = rng.randint(0, 2 * pad + 1, n)
    xs = rng.randint(0, 2 * pad + 1, n)
    flips = rng.rand(n) < 0.5
    fast = native.crop_mirror_batch(padded, h, w, ys, xs, flips)
    if fast is not None:
        return fast
    out = np.empty_like(x)
    for i in range(n):
        img = padded[i, ys[i] : ys[i] + h, xs[i] : xs[i] + w]
        out[i] = img[:, ::-1] if flips[i] else img
    return out


class Cifar10Data(ArrayDataset):
    def __init__(self, config: dict | None = None):
        config = config or {}
        path = config.get("data_path") or os.environ.get("CIFAR10_PATH")
        n_train = config.get("n_train", 2048)  # synthetic default size
        n_val = config.get("n_val", 512)
        s = config.get("image_size", 32)  # synthetic path only; real data is 32
        if path and os.path.exists(path):
            raw = np.load(path)
            xt = raw["x_train"].astype(np.float32) / 255.0
            xv = raw["x_test"].astype(np.float32) / 255.0
            yt = raw["y_train"].reshape(-1).astype(np.int32)
            yv = raw["y_test"].reshape(-1).astype(np.int32)
            self.synthetic = False
        else:
            xt, yt = _class_structured(
                n_train, (s, s, 3), 10, seed=0, noise=0.5, means_seed=0
            )
            xv, yv = _class_structured(
                n_val, (s, s, 3), 10, seed=1, noise=0.5, means_seed=0
            )
            # shift into [0,1]-ish range so normalization below is meaningful
            xt = 0.5 + 0.1 * xt
            xv = 0.5 + 0.1 * xv
            self.synthetic = True
        if config.get("normalize", "standard") == "tanh":
            # GAN mode: [-1, 1] to match a tanh generator's output support
            xt = xt * 2.0 - 1.0
            xv = xv * 2.0 - 1.0
        else:
            xt = (xt - MEAN) / STD
            xv = (xv - MEAN) / STD
        augment = pad_crop_mirror if config.get("augment", True) else None
        super().__init__(
            xt.astype(np.float32), yt, xv.astype(np.float32), yv,
            n_classes=10, augment_fn=augment,
        )
