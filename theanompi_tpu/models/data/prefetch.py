"""Background prefetch: the para_load equivalent (compute/input overlap).

Reference (unverified — SURVEY.md §2.1/§3.5): ``models/data/proc_load_mpi.py``
— each worker spawned a loader child via ``MPI.COMM_SELF.Spawn`` that read and
augmented the next ``.hkl`` batch while the GPU computed, handing batches over
an intercommunicator; the worker's ``train_iter`` "wait" segment measured the
residual stall.

TPU-native re-expression: no child processes or IPC — a daemon thread drains
the (numpy-producing, possibly augmenting) batch iterator into a small
bounded queue and eagerly ``device_put``s each batch onto the mesh, so host
read/augment/transfer overlaps device compute.  jax dispatch is async and
``device_put`` is thread-safe, which is all the machinery the reference's
process dance existed to obtain.  The trainer's "wait" segment still measures
the residual stall, keeping the Recorder's calc/comm/wait split comparable.
"""

from __future__ import annotations

import queue
import threading
import time

from theanompi_tpu.utils.helper_funcs import shard_batch

_END = object()


class PrefetchStallError(RuntimeError):
    """The source iterator produced nothing for ``stall_timeout`` seconds
    (ISSUE 4): the training thread gets a diagnosable error instead of a
    silent eternal block in ``queue.get`` — which a supervisor can restart
    and a watchdog would otherwise only catch by its coarser no-progress
    threshold."""


class Prefetcher:
    """Iterate ``it`` on a daemon thread, ``depth`` batches ahead.

    ``mesh`` set → batches are shard_batch'd (device transfer included in the
    overlap) and arrive as jax arrays; ``mesh=None`` → raw host batches.
    An exception in the source iterator is re-raised at the consuming site.

    ``stall_timeout`` (seconds, default None = block forever as before)
    bounds how long ``__next__`` waits on an empty queue before raising
    :class:`PrefetchStallError`.  ``fault_plan`` enables the deterministic
    ``prefetch:stall@N`` / ``prefetch:raise@N`` injection sites inside the
    worker (N = source batch ordinal).

    Checkpointable position (ISSUE 10): ``start_batch`` declares the global
    batch index of the FIRST item ``it`` will yield (the caller built the
    source fast-forwarded to that cursor), so fault-site ordinals stay
    global batch indices across a resume.  :meth:`state` reports
    ``consumed`` — the index of the first batch the *consumer* has not been
    handed yet.  Batches sitting in the queue (produced, possibly
    device-resident, but never returned from ``__next__``) are excluded by
    construction: a restore from this snapshot resumes at the first
    unconsumed batch, replaying nothing and skipping nothing.
    """

    def __init__(self, it, mesh=None, depth: int = 2, spec=None,
                 telemetry=None, stall_timeout: float | None = None,
                 fault_plan=None, start_batch: int = 0):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if stall_timeout is not None and stall_timeout <= 0:
            raise ValueError(
                f"prefetch stall_timeout must be positive or None, "
                f"got {stall_timeout}")
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._it = it
        # optional telemetry: each dequeue emits a span with the residual
        # queue depth, so a starving pipeline is visible in the trace as
        # long prefetch.dequeue spans at qsize 0
        self._telemetry = telemetry
        self._stall_timeout = stall_timeout
        self._err: BaseException | None = None
        self._stop = threading.Event()
        self._consumed = int(start_batch)

        def put(item) -> bool:
            """put that gives up when the consumer closed us."""
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def work():
            try:
                for i, item in enumerate(it, start=int(start_batch)):
                    if fault_plan is not None:
                        action = fault_plan.fire("prefetch", i)
                        if action == "stall":
                            # a hung source: produce nothing until closed
                            # (the consumer's stall_timeout is the witness)
                            while not self._stop.wait(0.05):
                                pass
                            return
                        if action == "raise":
                            from theanompi_tpu.resilience.faults import (
                                FaultInjected,
                            )

                            raise FaultInjected(
                                f"injected source failure at batch {i}")
                    if self._stop.is_set():
                        return
                    if mesh is not None:
                        item = shard_batch(mesh, item, spec=spec)
                    if not put(item):
                        return
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                put(_END)

        self._thread = threading.Thread(target=work, name="data-prefetch",
                                        daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def _get(self):
        """Dequeue honoring ``stall_timeout`` (None = block forever)."""
        if self._stall_timeout is None:
            return self._q.get()
        deadline = time.perf_counter() + self._stall_timeout
        while True:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                if self._telemetry is not None:
                    self._telemetry.instant(
                        "prefetch.stall", timeout_s=self._stall_timeout)
                raise PrefetchStallError(
                    f"no batch from the source iterator for "
                    f"{self._stall_timeout:g}s (loader thread alive: "
                    f"{self._thread.is_alive()}) — data pipeline stalled")
            try:
                # short slices so a concurrent close() is noticed promptly
                return self._q.get(timeout=min(0.25, remaining))
            except queue.Empty:
                continue

    def __next__(self):
        tel = self._telemetry
        t0 = time.perf_counter() if tel is not None else 0.0
        item = self._get()
        if item is _END:
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        if tel is not None:
            tel.emit_span("prefetch.dequeue", t0,
                          time.perf_counter() - t0, qsize=self._q.qsize())
        self._consumed += 1
        return item

    def state(self) -> dict:
        """Restart snapshot: ``consumed`` is the global index of the first
        batch the consumer has NOT received — in-flight queued batches are
        not counted, so restoring here neither replays nor skips data."""
        return {"consumed": self._consumed}

    def close(self) -> None:
        """Release the worker, drop queued (device-resident) batches, and
        CLOSE the source generator.

        Without this, an abandoned iterator leaves the thread blocked on a
        full queue with `depth` global batches pinned in HBM for the life
        of the process — and a generator-backed source (the shm worker
        ring holds its epoch lock while suspended at yield) would stay
        open until GC, blocking the next epoch.
        """
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # closing a generator mid-execution from another thread raises,
            # so we cannot free the source here — say so instead of leaving
            # a silent mystery (a held shm-pool epoch lock surfaces later
            # as "already serving an epoch")
            import warnings

            warnings.warn(
                "Prefetcher.close(): worker still inside the source "
                "iterator after 5s; source generator left open",
                RuntimeWarning,
                stacklevel=2,
            )
            return
        close = getattr(self._it, "close", None)
        if close:
            close()


def prefetch(it, mesh=None, depth: int = 2, spec=None, telemetry=None,
             stall_timeout: float | None = None, fault_plan=None,
             start_batch: int = 0):
    """``depth=0`` disables prefetching (pass-through), else wraps in a
    :class:`Prefetcher`."""
    if depth == 0:
        return it
    return Prefetcher(it, mesh=mesh, depth=depth, spec=spec,
                      telemetry=telemetry, stall_timeout=stall_timeout,
                      fault_plan=fault_plan, start_batch=start_batch)
