"""Background prefetch: the para_load equivalent (compute/input overlap).

Reference (unverified — SURVEY.md §2.1/§3.5): ``models/data/proc_load_mpi.py``
— each worker spawned a loader child via ``MPI.COMM_SELF.Spawn`` that read and
augmented the next ``.hkl`` batch while the GPU computed, handing batches over
an intercommunicator; the worker's ``train_iter`` "wait" segment measured the
residual stall.

TPU-native re-expression: no child processes or IPC — a daemon thread drains
the (numpy-producing, possibly augmenting) batch iterator into a small
bounded queue and eagerly ``device_put``s each batch onto the mesh, so host
read/augment/transfer overlaps device compute.  jax dispatch is async and
``device_put`` is thread-safe, which is all the machinery the reference's
process dance existed to obtain.  The trainer's "wait" segment still measures
the residual stall, keeping the Recorder's calc/comm/wait split comparable.
"""

from __future__ import annotations

import queue
import threading
import time

from theanompi_tpu.utils.helper_funcs import shard_batch

_END = object()


class Prefetcher:
    """Iterate ``it`` on a daemon thread, ``depth`` batches ahead.

    ``mesh`` set → batches are shard_batch'd (device transfer included in the
    overlap) and arrive as jax arrays; ``mesh=None`` → raw host batches.
    An exception in the source iterator is re-raised at the consuming site.
    """

    def __init__(self, it, mesh=None, depth: int = 2, spec=None,
                 telemetry=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._it = it
        # optional telemetry: each dequeue emits a span with the residual
        # queue depth, so a starving pipeline is visible in the trace as
        # long prefetch.dequeue spans at qsize 0
        self._telemetry = telemetry
        self._err: BaseException | None = None
        self._stop = threading.Event()

        def put(item) -> bool:
            """put that gives up when the consumer closed us."""
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def work():
            try:
                for item in it:
                    if self._stop.is_set():
                        return
                    if mesh is not None:
                        item = shard_batch(mesh, item, spec=spec)
                    if not put(item):
                        return
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                put(_END)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        tel = self._telemetry
        t0 = time.perf_counter() if tel is not None else 0.0
        item = self._q.get()
        if item is _END:
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        if tel is not None:
            tel.emit_span("prefetch.dequeue", t0,
                          time.perf_counter() - t0, qsize=self._q.qsize())
        return item

    def close(self) -> None:
        """Release the worker, drop queued (device-resident) batches, and
        CLOSE the source generator.

        Without this, an abandoned iterator leaves the thread blocked on a
        full queue with `depth` global batches pinned in HBM for the life
        of the process — and a generator-backed source (the shm worker
        ring holds its epoch lock while suspended at yield) would stay
        open until GC, blocking the next epoch.
        """
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # closing a generator mid-execution from another thread raises,
            # so we cannot free the source here — say so instead of leaving
            # a silent mystery (a held shm-pool epoch lock surfaces later
            # as "already serving an epoch")
            import warnings

            warnings.warn(
                "Prefetcher.close(): worker still inside the source "
                "iterator after 5s; source generator left open",
                RuntimeWarning,
                stacklevel=2,
            )
            return
        close = getattr(self._it, "close", None)
        if close:
            close()


def prefetch(it, mesh=None, depth: int = 2, spec=None, telemetry=None):
    """``depth=0`` disables prefetching (pass-through), else wraps in a
    :class:`Prefetcher`."""
    if depth == 0:
        return it
    return Prefetcher(it, mesh=mesh, depth=depth, spec=spec,
                      telemetry=telemetry)
