"""Streaming tokenized LM dataset with checkpointable mixture cursors.

The in-memory per-epoch-shuffle :class:`~theanompi_tpu.models.data.base.
Dataset` model does not fit LM-scale corpora: a streaming corpus has no
natural epoch, is read as sharded token files too large to shuffle whole,
and is usually a *mixture* of sources (web / code / books) sampled by
weight.  This module supplies that shape under the ISSUE 10 iterator-state
contract:

- **Sources**: each source is an ordered list of 1-D token shards
  (``*.npy`` int arrays, read via ``read_with_retry``) or a deterministic
  synthetic token stream (zero-egress stand-in with learnable bigram
  structure).  A source is addressed in fixed non-overlapping *windows* of
  ``seq_len + 1`` tokens (targets are inputs shifted by one); the ragged
  tail of each shard is dropped so the window→shard mapping never depends
  on neighbouring shards.
- **Mixture**: each sample draws its source from the mixture weights via
  ``derive_seed("mix", seed, epoch, global_sample_index)`` — a pure
  function of the sample's position, never of iteration history or of the
  batch size (so an elastic resume that re-batches the stream keeps the
  identical flat sample order).
- **Cursors**: every source advances a window cursor as its windows are
  consumed; cursors carry *across* nominal epochs (the stream continues —
  it does not rewind), which makes them genuinely stateful.
  :meth:`StreamTokenDataset.state` returns the start-of-epoch cursor base
  plus the live mixture weights; a mid-epoch resume restores that base and
  fast-forwards by replaying only the cheap integer mixture *choices* for
  the consumed batches — no token is ever re-read, no window replayed or
  skipped.  The state is device-count-independent: an elastic mesh8→4
  resume recomputes its batch cursor from the sample cursor and consumes
  the identical remaining window order.

Config keys (all optional): ``seq_len``; ``stream_sources`` — list of
``{"name", "weight", "path"}`` (dir of token shards) or ``{"name",
"weight", "tokens", "vocab", "seed"}`` (synthetic); ``n_train`` — nominal
sequences per epoch (streams need a nominal epoch length for the trainer's
epoch loop); ``n_val``; ``loader_workers`` — > 0 warm-loads file-source
shards in parallel through the :class:`ShmShardPool` token mode.
"""

from __future__ import annotations

import os

import numpy as np

from theanompi_tpu.models.data.base import (
    Dataset,
    derive_seed,
    read_with_retry,
)


def load_token_shard(path: str) -> np.ndarray:
    """One token shard as a flat int32 array (bounded-retry read)."""
    return read_with_retry(
        lambda: np.asarray(np.load(path)).astype(np.int32).ravel(),
        what=path)


class _FileTokenSource:
    """Sharded on-disk token stream with window addressing."""

    def __init__(self, name: str, path: str, seq_len: int):
        self.name = name
        self.window_len = seq_len + 1
        shards = sorted(f for f in os.listdir(path)
                        if f.endswith(".npy"))
        if not shards:
            raise FileNotFoundError(f"no .npy token shards under {path}")
        self.shard_paths = [os.path.join(path, f) for f in shards]
        # headers only (mmap): counts for the window→shard map, no payload
        lens = [int(read_with_retry(
            lambda p=p: np.load(p, mmap_mode="r").shape[0], what=p))
            for p in self.shard_paths]
        self.shard_windows = [n // self.window_len for n in lens]
        self.n_windows = sum(self.shard_windows)
        if self.n_windows == 0:
            raise ValueError(
                f"source {name!r}: no shard holds a full window "
                f"({self.window_len} tokens)")
        self.vocab_hint = None  # unknown without reading payloads
        self._cache: dict[int, np.ndarray] = {}
        self._cache_order: list[int] = []

    def cache_shard(self, j: int, toks: np.ndarray) -> None:
        """Install a pre-loaded shard (the pool warm-load path)."""
        self._cache[j] = toks
        self._cache_order.append(j)

    def _shard(self, j: int) -> np.ndarray:
        toks = self._cache.get(j)
        if toks is None:
            toks = load_token_shard(self.shard_paths[j])
            self.cache_shard(j, toks)
            # cursor access is sequential per source: keep a few shards
            while len(self._cache_order) > 4:
                self._cache.pop(self._cache_order.pop(0), None)
        return toks

    def window(self, w: int) -> np.ndarray:
        w %= self.n_windows
        for j, nw in enumerate(self.shard_windows):
            if w < nw:
                start = w * self.window_len
                return self._shard(j)[start:start + self.window_len]
            w -= nw
        raise AssertionError("unreachable: window index out of range")


class _SyntheticTokenSource:
    """Deterministic procedural token stream (learnable sparse bigram).

    Same structure as the large-vocab branch of
    ``SyntheticSequenceDataset``: every token has 32 successors at
    ``(a*cur + c + j*j) % vocab`` drawn from one peaked categorical — O(1)
    memory, perplexity can drop well below vocab.  Window ``w`` is a pure
    function of (seed, w): chains are generated per-window from a keyed
    rng, so any window is recomputable in isolation.
    """

    def __init__(self, name: str, n_tokens: int, vocab: int, seed: int,
                 seq_len: int):
        self.name = name
        self.window_len = seq_len + 1
        self.vocab_hint = vocab
        self.vocab = vocab
        self.n_windows = max(1, int(n_tokens) // self.window_len)
        rng = np.random.RandomState(derive_seed("stream-synth", seed, name))
        self._a = 2 * rng.randint(1, max(2, vocab // 2)) + 1
        self._c = rng.randint(vocab)
        wl = np.sort(rng.randn(32) * 2.0)[::-1]
        w = np.exp(wl) / np.exp(wl).sum()
        self._cdf = w.cumsum()
        self._seed = seed

    def window(self, w: int) -> np.ndarray:
        w %= self.n_windows
        r = np.random.RandomState(derive_seed("window", self._seed,
                                              self.name, w))
        out = np.zeros(self.window_len, np.int32)
        out[0] = r.randint(0, self.vocab)
        j2 = np.arange(32, dtype=np.int64) ** 2
        for t in range(self.window_len - 1):
            j = min(int((r.rand() > self._cdf).sum()), 31)
            out[t + 1] = (self._a * int(out[t]) + self._c + j2[j]) % self.vocab
        return out


class StreamTokenDataset(Dataset):
    """Multi-source windowed token stream feeding ``transformer_lm``."""

    def __init__(self, config: dict | None = None):
        config = config or {}
        self.seq_len = int(config.get("seq_len", 128))
        self.loader_workers = int(config.get("loader_workers", 0))
        specs = config.get("stream_sources")
        if not specs:
            # zero-egress default: a two-source synthetic mixture, so the
            # mixture/cursor machinery is exercised even out of the box
            vocab = int(config.get("vocab", 256))
            specs = [
                {"name": "syn-a", "weight": 0.75, "tokens": 65536,
                 "vocab": vocab, "seed": 11},
                {"name": "syn-b", "weight": 0.25, "tokens": 65536,
                 "vocab": vocab, "seed": 13},
            ]
        self._sources = []
        weights = []
        for s in specs:
            w = float(s.get("weight", 1.0))
            if w <= 0:
                raise ValueError(f"source {s.get('name')!r}: weight {w} <= 0")
            if "path" in s:
                src = _FileTokenSource(s["name"], s["path"], self.seq_len)
            else:
                src = _SyntheticTokenSource(
                    s["name"], int(s.get("tokens", 65536)),
                    int(s.get("vocab", config.get("vocab", 256))),
                    int(s.get("seed", 0)), self.seq_len)
            self._sources.append(src)
            weights.append(w)
        names = [s.name for s in self._sources]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate source names: {names}")
        self._names = names
        tot = sum(weights)
        self._weights = [w / tot for w in weights]
        hints = [s.vocab_hint for s in self._sources if s.vocab_hint]
        self.vocab = int(config.get("vocab", max(hints) if hints else 256))
        self.n_classes = self.vocab
        self.sample_shape = (self.seq_len,)
        # nominal epoch length: streams have none, the trainer's epoch loop
        # needs one (n_train sequences per nominal epoch)
        self.n_train = int(config.get("n_train", 512))
        self.n_val = int(config.get("n_val", 128))
        # stream position: start-of-epoch cursor base, per source.  Live
        # iteration works on a COPY; the base advances only when an epoch
        # generator is exhausted (or via set_state), so a checkpoint taken
        # mid-epoch pairs the base with the trainer's consumed-batch cursor
        # regardless of how far a prefetcher ran ahead.
        self._base_cursors = {n: 0 for n in names}
        self._base_epoch = 0
        self._warmed = False

    # -- checkpointable state (ISSUE 10 contract) ----------------------------
    def state(self) -> dict:
        return {
            "version": 1,
            "weights": {n: w for n, w in zip(self._names, self._weights)},
            "cursors": dict(self._base_cursors),
            "base_epoch": int(self._base_epoch),
        }

    def set_state(self, state: dict) -> None:
        if not state:
            return
        weights = state.get("weights")
        if weights:
            missing = [n for n in self._names if n not in weights]
            if missing:
                raise ValueError(
                    f"stream state missing sources {missing} "
                    f"(have {sorted(weights)})")
            ws = [float(weights[n]) for n in self._names]
            tot = sum(ws)
            self._weights = [w / tot for w in ws]
        for n, c in (state.get("cursors") or {}).items():
            if n in self._base_cursors:
                self._base_cursors[n] = int(c)
        self._base_epoch = int(state.get("base_epoch", 0))

    def set_mixture_weights(self, weights: dict) -> None:
        """Runtime mixture re-weighting (curriculum).  Takes effect at the
        next ``train_batches`` call (epoch granularity — the weights in
        effect for an epoch are snapshotted at generator creation, so a
        resumed replay of that epoch uses the checkpointed weights, not
        whatever was installed later)."""
        ws = [float(weights[n]) for n in self._names]
        if any(w <= 0 for w in ws):
            raise ValueError(f"weights must be positive: {weights}")
        tot = sum(ws)
        self._weights = [w / tot for w in ws]

    # -- iteration -----------------------------------------------------------
    def _choices(self, batch_size, epoch, seed, batch, weights):
        """Batch ``batch``'s source index per element.

        Keyed per GLOBAL SAMPLE index (``batch * batch_size + j``), not per
        batch: an elastic resume re-batches the same sample stream at a
        different global batch size, and only sample-keyed choices keep the
        flat sample order identical across that re-batching (the
        device-count-independence the sample cursor promises).  The uniform
        draw is ``derive_seed`` itself mapped into [0, 1) — one hash per
        sample, no RandomState construction."""
        cdf = np.cumsum(weights)
        out = np.empty(batch_size, np.int64)
        base = int(batch) * int(batch_size)
        for j in range(batch_size):
            u = derive_seed("mix", seed, epoch, base + j) / float(2**31)
            out[j] = min(int(np.searchsorted(cdf, u, side="right")),
                         len(self._sources) - 1)
        return out

    def _warm(self):
        """Parallel warm-load of file-source shards through the shm pool
        token mode (spawn cost paid once; epoch iteration then hits the
        in-memory caches)."""
        self._warmed = True
        file_srcs = [s for s in self._sources
                     if isinstance(s, _FileTokenSource)]
        if self.loader_workers <= 0 or not file_srcs:
            return
        from theanompi_tpu.models.data.shm_loader import ShmShardPool

        jobs = [(src, j) for src in file_srcs
                for j in range(len(src.shard_paths))]
        nbytes = max(4 * int(read_with_retry(
            lambda p=p: np.load(p, mmap_mode="r").shape[0], what=p))
            for src in file_srcs for p in src.shard_paths)
        pool = ShmShardPool(1, 1, self.loader_workers, slot_nbytes=nbytes)
        try:
            tasks = [(("tokens", src.shard_paths[j]), 0) for src, j in jobs]
            for (src, j), (toks, _y) in zip(jobs, pool.run(tasks)):
                src.cache_shard(j, toks)
        finally:
            pool.close()

    def train_batches(self, batch_size, epoch, seed=0, start_batch=0):
        if not self._warmed:
            self._warm()
        weights = list(self._weights)  # snapshot: one epoch, one mixture
        cursors = dict(self._base_cursors)
        names = self._names
        # fast-forward by cursor arithmetic: replay only the integer
        # mixture choices of the consumed batches — no token reads
        for i in range(int(start_batch)):
            for s in self._choices(batch_size, epoch, seed, i, weights):
                cursors[names[s]] += 1
        n_batches = self.n_train // batch_size
        for i in range(int(start_batch), n_batches):
            choice = self._choices(batch_size, epoch, seed, i, weights)
            xs = np.empty((batch_size, self.seq_len + 1), np.int32)
            for j, s in enumerate(choice):
                src = self._sources[int(s)]
                xs[j] = src.window(cursors[src.name])
                cursors[src.name] += 1
            yield {"x": xs[:, :-1], "y": xs[:, 1:]}
        # nominal epoch complete: the stream does not rewind — the next
        # epoch continues from here
        self._base_cursors = cursors
        self._base_epoch = int(epoch) + 1

    def val_batches(self, batch_size):
        """Deterministic held-aside windows: round-robin over sources at
        derived window indices — no cursor motion, identical every call."""
        if not self._warmed:
            self._warm()
        n_srcs = len(self._sources)
        for i in range(self.n_val // batch_size):
            xs = np.empty((batch_size, self.seq_len + 1), np.int32)
            for j in range(batch_size):
                k = i * batch_size + j
                src = self._sources[k % n_srcs]
                # offset past the low windows train consumes first
                w = (src.n_windows // 2 + derive_seed("val", k)) \
                    % src.n_windows
                xs[j] = src.window(w)
            yield {"x": xs[:, :-1], "y": xs[:, 1:]}
