"""Persistent process-parallel shard loader with shared-memory handoff.

The reference's ``para_load`` (SURVEY.md §3.5) was a long-lived loader
process filling pinned buffers behind a socket handshake so the GPU never
waited on JPEG/crop work.  This is its host-side analogue for the TPU
runtime: N worker processes each load one shard, run the C crop/mirror
kernel and the within-shard shuffle, and write the result straight into a
slot of one ``multiprocessing.shared_memory`` ring — no pickling of image
tensors (a plain ``Pool.imap`` pipes ~19 MB per shard through pickle and
measured SLOWER than inline; the ring costs one parent-side memcpy).

Design constraints this encodes:

- **spawn, not fork**: the parent is a JAX process with live XLA/dispatch
  threads; forking it risks the classic held-lock deadlock (Python warns
  exactly this).  Spawned workers re-import the interpreter (~8 s on this
  image — sitecustomize pulls in jax), which is why the pool is
  **persistent**: created once per dataset, reused every epoch, closed by
  ``Dataset.cleanup()``.
- **slot flow control**: a slot is handed to a worker only after the
  consumer finished with it, so the ring bounds memory however far the
  workers run ahead.
- **determinism**: results are re-ordered to shard order and each task
  carries its own seed, so a fixed seed list reproduces the stream
  bit-for-bit regardless of worker timing.
"""

from __future__ import annotations

import multiprocessing as mp
import threading

import numpy as np


def _worker(task_q, result_q, shm_name, slot_nbytes, image_size):
    from multiprocessing import shared_memory

    from theanompi_tpu.models.data.imagenet import (
        _load_from_spec,
        random_crop_mirror,
    )

    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        while True:
            task = task_q.get()
            if task is None:
                return
            idx, spec, seed, slot = task
            if spec[0] == "tokens":
                # token mode (stream.py warm-load): read one flat int32
                # token shard into the slot — no augmentation, no labels
                from theanompi_tpu.models.data.stream import (
                    load_token_shard,
                )

                toks = load_token_shard(spec[1])
                out = np.ndarray(toks.shape, np.int32,
                                 buffer=shm.buf[slot * slot_nbytes:])
                out[:] = toks
                result_q.put((idx, slot, toks.shape, "int32", None))
                continue
            x, y = _load_from_spec(spec)
            rng = np.random.RandomState(seed)
            x = random_crop_mirror(x, image_size, rng)
            per = rng.permutation(len(x))
            x, y = x[per], y[per]
            out = np.ndarray(x.shape, np.uint8,
                             buffer=shm.buf[slot * slot_nbytes:])
            out[:] = x
            # lint: donated-escape-ok — y is fancy-indexed above (y[per]):
            # a fresh host-owned array, never a device-buffer view
            result_q.put((idx, slot, x.shape, "uint8", np.asarray(y)))
    finally:
        shm.close()


class ShmShardPool:
    """Reusable worker ring: ``run(tasks)`` yields one epoch's augmented
    (x, y) shards in order; ``close()`` tears the workers down.

    ``tasks``: list of (spec, seed) with specs from
    ``_ShardSet.spec``/``_SyntheticShards.spec``, or ``("tokens", path)``
    specs (token shards for ``stream.py`` — yielded as (int32 tokens,
    None)).  Yielded ``x`` arrays are fresh copies (the ring slot is
    recycled immediately).  One epoch at a time: a second ``run`` while
    one is active raises (close the first generator — the prefetcher
    does).

    ``slot_nbytes`` overrides the image-shard slot-size formula for
    non-image payloads (the token mode).
    """

    def __init__(self, image_size: int, shard_size: int, workers: int,
                 slots: int | None = None, ctx_method: str = "spawn",
                 slot_nbytes: int | None = None):
        from multiprocessing import shared_memory

        self.image_size = image_size
        self.workers = max(1, workers)
        self.slots = slots or 2 * self.workers
        self.slot_nbytes = (slot_nbytes if slot_nbytes is not None
                            else shard_size * image_size * image_size * 3)
        self._shm = shared_memory.SharedMemory(
            create=True, size=max(1, self.slots * self.slot_nbytes))
        ctx = mp.get_context(ctx_method)
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._procs = [
            ctx.Process(target=_worker, daemon=True,
                        args=(self._task_q, self._result_q, self._shm.name,
                              self.slot_nbytes, image_size))
            for _ in range(self.workers)
        ]
        for p in self._procs:
            p.start()
        self._closed = False
        self._broken = False
        self._busy = threading.Lock()

    def _get_result(self):
        """result_q.get with worker-liveness checks: a dead worker (OOM
        kill, exception on a corrupt shard) must raise, not hang the
        training loop forever."""
        import queue as _queue

        while True:
            try:
                return self._result_q.get(timeout=5)
            except _queue.Empty:
                dead = [p for p in self._procs if not p.is_alive()]
                if dead:
                    raise RuntimeError(
                        f"ShmShardPool: {len(dead)} worker(s) died "
                        f"(exitcodes {[p.exitcode for p in dead]}); "
                        "a shard load/augment likely raised — see worker "
                        "stderr"
                    ) from None

    def run(self, tasks):
        if self._closed or self._broken:
            raise RuntimeError("ShmShardPool is closed or broken")
        if not self._busy.acquire(blocking=False):
            raise RuntimeError(
                "ShmShardPool already serving an epoch; close the previous"
                " batch generator first"
            )
        try:
            tasks = list(tasks)
            free = list(range(self.slots))
            next_submit = 0

            def submit():
                nonlocal next_submit
                if next_submit < len(tasks) and free:
                    spec, seed = tasks[next_submit]
                    self._task_q.put(
                        (next_submit, spec, int(seed), free.pop()))
                    next_submit += 1

            for _ in range(min(self.slots, len(tasks))):
                submit()
            pending: dict[int, tuple] = {}
            served = 0
            try:
                for want in range(len(tasks)):
                    while want not in pending:
                        idx, slot, shape, dt, y = self._get_result()
                        pending[idx] = (slot, shape, dt, y)
                    slot, shape, dt, y = pending.pop(want)
                    view = np.ndarray(
                        shape, np.dtype(dt),
                        buffer=self._shm.buf[slot * self.slot_nbytes:])
                    x = view.copy()  # the slot is recycled right after
                    del view  # shm.buf views must die before close/unlink
                    free.append(slot)
                    submit()
                    served += 1
                    yield x, y
            finally:
                # early close (GeneratorExit): drain in-flight results so
                # the next epoch starts from an empty ring; if a worker
                # died, give up draining (the pool is broken either way)
                inflight = next_submit - served - len(pending)
                try:
                    for _ in range(inflight):
                        self._get_result()
                except RuntimeError:
                    # a worker died: mark broken (close() still tears the
                    # survivors + shm down — _closed would no-op it)
                    self._broken = True
                pending.clear()
        finally:
            self._busy.release()

    def close(self):
        if self._closed:
            return
        self._closed = True
        for _ in self._procs:
            self._task_q.put(None)
        for p in self._procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
        try:
            self._shm.close()
            self._shm.unlink()
        except FileNotFoundError:  # lint: swallow-ok — already unlinked
            pass
