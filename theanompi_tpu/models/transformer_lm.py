"""Decoder-only transformer LM with dp × tp × sp sharding.

Beyond-reference extension (SURVEY.md §5 marks long-context absent upstream)
that exercises the framework's full parallelism surface: data parallelism
(the rules), tensor parallelism (Megatron-style column/row splits over the
``model`` axis — :mod:`theanompi_tpu.parallel.tensor`), and sequence/context
parallelism (ring attention over the ``seq`` axis —
:mod:`theanompi_tpu.parallel.ring_attention`), all inside one BSP step.

Config: ``dim``/``heads``/``n_layers``/``seq_len``; ``seq_parallel=True``
shards batches ``P(data, seq)`` and adds ``seq`` to the gradient reduction.
Trains on PTB (or the synthetic bigram stream) like the LSTM LM.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.lstm import PTBData
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L
from theanompi_tpu.ops.attention import MultiHeadAttention, PositionEmbedding
from theanompi_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS
from theanompi_tpu.parallel.tensor import (
    TP_RULES,
    ColumnParallelDense,
    RowParallelDense,
    specs_from_rules,
)
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class _Block(L.Layer):
    """Pre-norm transformer block: LN→MHA→res, LN→MLP(4x, gelu)→res."""

    dim: int
    heads: int
    dropout: float = 0.0
    attn_impl: str = "auto"

    def _subs(self):
        return (
            ("ln1", L.LayerNorm()),
            ("attn", MultiHeadAttention(self.dim, self.heads, causal=True,
                                        impl=self.attn_impl)),
            ("ln2", L.LayerNorm()),
            ("up", ColumnParallelDense(4 * self.dim, w_init=init_lib.normal(0.02))),
            ("down", RowParallelDense(self.dim, w_init=init_lib.normal(0.02))),
        )

    def init(self, key, in_shape):
        params, state = {}, {}
        keys = jax.random.split(key, 5)
        shape = in_shape
        for (name, layer), k in zip(self._subs(), keys):
            if name in ("ln1", "ln2", "attn"):
                p, s, _ = layer.init(k, in_shape)
            elif name == "up":
                p, s, up_shape = layer.init(k, in_shape)
            else:
                p, s, _ = layer.init(k, up_shape)
            if p:
                params[name] = p
            if s:
                state[name] = s
        return params, state, tuple(shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        subs = dict(self._subs())
        rngs = (
            jax.random.split(rng, 2) if rng is not None else (None, None)
        )
        drop = L.Dropout(self.dropout)

        h, _ = subs["ln1"].apply(params["ln1"], {}, x)
        h, _ = subs["attn"].apply(params["attn"], {}, h, train=train)
        h, _ = drop.apply({}, {}, h, train=train, rng=rngs[0])
        x = x + h
        h, _ = subs["ln2"].apply(params["ln2"], {}, x)
        h, _ = subs["up"].apply(params["up"], {}, h)
        h = jax.nn.gelu(h)
        h, _ = subs["down"].apply(params["down"], {}, h)
        h, _ = drop.apply({}, {}, h, train=train, rng=rngs[1])
        return x + h, state


class TransformerLM(SupervisedModel):
    default_config = {
        "batch_size": 8,
        "n_epochs": 10,
        "lr": 1e-3,
        "momentum": 0.9,
        "grad_clip": 1.0,
        "seq_len": 256,
        "dim": 256,
        "heads": 8,
        "n_layers": 4,
        "dropout": 0.1,
        "seq_parallel": False,
        # "auto": pallas flash attention when shapes allow (TPU-compiled,
        # interpreted on CPU); "blockwise"/"pallas" force a path
        "attn_impl": "auto",
    }

    def build_data(self):
        return PTBData(self.config)

    def build_net(self):
        cfg = self.config
        layers: list[L.Layer] = [
            L.Embedding(self.data.vocab, cfg["dim"],
                        w_init=init_lib.normal(0.02)),
            PositionEmbedding(cfg["seq_len"], cfg["dim"]),
        ]
        for _ in range(cfg["n_layers"]):
            layers.append(_Block(cfg["dim"], cfg["heads"], cfg["dropout"],
                                 attn_impl=cfg["attn_impl"]))
        layers += [
            L.LayerNorm(),
            L.Dense(self.data.vocab, w_init=init_lib.glorot_normal),
        ]
        return L.Sequential(layers), (cfg["seq_len"],)

    # -- sharding ------------------------------------------------------------
    def param_specs(self, params):
        return specs_from_rules(params, TP_RULES)

    def batch_partition(self) -> P:
        if self.config["seq_parallel"]:
            return P(DATA_AXIS, SEQ_AXIS)
        return P(DATA_AXIS)

    def grad_reduce_axes(self) -> tuple[str, ...]:
        if self.config["seq_parallel"]:
            return (DATA_AXIS, SEQ_AXIS)
        return (DATA_AXIS,)

    def loss_fn(self, params, state, batch, rng, train: bool):
        loss, (new_state, metrics) = super().loss_fn(
            params, state, batch, rng, train
        )
        metrics = dict(metrics)
        metrics["perplexity"] = jnp.exp(metrics["cost"])
        return loss, (new_state, metrics)
