"""Decoder-only transformer LM with dp × tp × sp sharding.

Beyond-reference extension (SURVEY.md §5 marks long-context absent upstream)
that exercises the framework's full parallelism surface: data parallelism
(the rules), tensor parallelism (Megatron-style column/row splits over the
``model`` axis — :mod:`theanompi_tpu.parallel.tensor`), and sequence/context
parallelism (ring attention over the ``seq`` axis —
:mod:`theanompi_tpu.parallel.ring_attention`), all inside one BSP step.

Config: ``dim``/``heads``/``n_layers``/``seq_len``; ``seq_parallel=True``
shards batches ``P(data, seq)`` and adds ``seq`` to the gradient reduction.
Trains on PTB (or the synthetic bigram stream) like the LSTM LM.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.lstm import PTBData
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L
from theanompi_tpu.ops import quant
from theanompi_tpu.ops.attention import MultiHeadAttention, PositionEmbedding
from theanompi_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS
from theanompi_tpu.parallel.tensor import (
    TP_RULES,
    ColumnParallelDense,
    RowParallelDense,
    specs_from_rules,
)
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class _Block(L.Layer):
    """Pre-norm transformer block: LN→MHA→res, LN→FFN→res.

    The FFN half is a hook (``_ffn_subs``/``_apply_ffn``) so variants
    (:class:`_MoEBlock`) swap only that segment instead of copying the
    residual/LN/dropout wiring."""

    dim: int
    heads: int
    dropout: float = 0.0
    attn_impl: str = "auto"

    def _ffn_subs(self):
        w02 = init_lib.normal(0.02)
        return (
            ("up", ColumnParallelDense(4 * self.dim, w_init=w02)),
            ("down", RowParallelDense(self.dim, w_init=w02)),
        )

    def _subs(self):
        return (
            ("ln1", L.LayerNorm()),
            ("attn", MultiHeadAttention(self.dim, self.heads, causal=True,
                                        impl=self.attn_impl)),
            ("ln2", L.LayerNorm()),
            *self._ffn_subs(),
        )

    def _apply_ffn(self, subs, params, state, h, train):
        """-> (h, ffn_state); the MLP default carries no state."""
        h, _ = subs["up"].apply(params["up"], {}, h)
        h = jax.nn.gelu(h)
        h, _ = subs["down"].apply(params["down"], {}, h)
        return h, {}

    def init(self, key, in_shape):
        params, state = {}, {}
        subs = self._subs()
        ffn_names = {n for n, _ in self._ffn_subs()}
        keys = jax.random.split(key, len(subs))
        shape = tuple(in_shape)  # chained through the FFN segment only
        for (name, layer), k in zip(subs, keys):
            p, s, out = layer.init(k, shape if name in ffn_names else in_shape)
            if name in ffn_names:
                shape = out
            if p:
                params[name] = p
            if s:
                state[name] = s
        return params, state, tuple(in_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        subs = dict(self._subs())
        rngs = (
            jax.random.split(rng, 2) if rng is not None else (None, None)
        )
        drop = L.Dropout(self.dropout)

        h, _ = subs["ln1"].apply(params["ln1"], {}, x)
        h, _ = subs["attn"].apply(params["attn"], {}, h, train=train)
        h, _ = drop.apply({}, {}, h, train=train, rng=rngs[0])
        x = x + h
        h, _ = subs["ln2"].apply(params["ln2"], {}, x)
        h, ffn_state = self._apply_ffn(subs, params, state, h, train)
        h, _ = drop.apply({}, {}, h, train=train, rng=rngs[1])
        return x + h, ffn_state

    # -- serving path (ISSUE 6) ----------------------------------------------
    # Both steps reuse the training block's exact sub-layers (same params,
    # same LN/residual wiring, dropout off); only the attention context
    # comes from the duck-typed paged KV cache the serving engine passes in
    # (:class:`theanompi_tpu.serving.kv_cache.PagedKVCache` — models never
    # import serving, so the dependency edge stays serving -> models).

    def prefill_step(self, params, x, cache, layer_idx, table_row):
        """Full-prompt forward of one block: writes this layer's K/V into
        the cache, attends causally *within* the prompt via the training
        attention dispatch (pallas flash on TPU when the shape gate admits).
        ``x`` ``[1, P_pad, D]`` -> (y, cache')."""
        subs = dict(self._subs())
        attn = subs["attn"]
        h, _ = subs["ln1"].apply(params["ln1"], {}, x)
        q, k, v = attn.project_qkv(params["attn"], h)
        cache = cache.write_prefill(layer_idx, k, v, table_row)
        ctx = attn.attend(q, k, v)
        h = attn.project_out(
            params["attn"], ctx.reshape(x.shape[0], x.shape[1], -1))
        x = x + h
        h, _ = subs["ln2"].apply(params["ln2"], {}, x)
        h, _ = self._apply_ffn(subs, params, {}, h, False)
        return x + h, cache

    def prefill_suffix_step(self, params, x, cache, layer_idx, suffix_row,
                            full_row, prefix_len):
        """Partial-prefill forward of one block (ISSUE 17): ``x``
        ``[1, S_pad, D]`` holds only the UNCACHED suffix (absolute
        positions ``prefix_len..``); this layer's suffix K/V is written
        into ``suffix_row``'s blocks and the queries attend over the
        sequence's FULL row — the cached-prefix blocks included — via the
        paged gather.  -> (y, cache')."""
        subs = dict(self._subs())
        attn = subs["attn"]
        h, _ = subs["ln1"].apply(params["ln1"], {}, x)
        q, k, v = attn.project_qkv(params["attn"], h)
        cache = cache.write_prefill(layer_idx, k, v, suffix_row)
        ctx = cache.attend_prefill(layer_idx, q, full_row, prefix_len)
        h = attn.project_out(
            params["attn"], ctx.reshape(x.shape[0], x.shape[1], -1))
        x = x + h
        h, _ = subs["ln2"].apply(params["ln2"], {}, x)
        h, _ = self._apply_ffn(subs, params, {}, h, False)
        return x + h, cache

    def decode_step(self, params, x, cache, layer_idx, positions):
        """One-token incremental forward of one block: appends this layer's
        K/V at ``positions`` and attends over the cached context.
        ``x`` ``[B, 1, D]``, ``positions`` ``[B]`` -> (y, cache')."""
        subs = dict(self._subs())
        attn = subs["attn"]
        h, _ = subs["ln1"].apply(params["ln1"], {}, x)
        q, k, v = attn.project_qkv(params["attn"], h)
        cache = cache.write_decode(layer_idx, k[:, 0], v[:, 0], positions)
        ctx = cache.attend_decode(layer_idx, q[:, 0], positions)
        h = attn.project_out(
            params["attn"], ctx.reshape(x.shape[0], 1, -1))
        x = x + h
        h, _ = subs["ln2"].apply(params["ln2"], {}, x)
        h, _ = self._apply_ffn(subs, params, {}, h, False)
        return x + h, cache


@dataclasses.dataclass(frozen=True)
class _MoEBlock(_Block):
    """:class:`_Block` with a switch-routed MoE FFN; the MoE's load-balance
    aux loss rides in state under ``moe.aux``."""

    n_experts: int = 8
    capacity_factor: float = 1.25

    def _ffn_subs(self):
        from theanompi_tpu.ops.moe import MoEFFN

        return (("moe", MoEFFN(self.dim, self.n_experts,
                               capacity_factor=self.capacity_factor)),)

    def _apply_ffn(self, subs, params, state, h, train):
        h, moe_state = subs["moe"].apply(
            params["moe"], state.get("moe", {}), h, train=train
        )
        return h, {"moe": moe_state}


class TransformerLM(SupervisedModel):
    default_config = {
        "batch_size": 8,
        "n_epochs": 10,
        "lr": 1e-3,
        "momentum": 0.9,
        "grad_clip": 1.0,
        "seq_len": 256,
        "dim": 256,
        "heads": 8,
        "n_layers": 4,
        "dropout": 0.1,
        "seq_parallel": False,
        # "auto": pallas flash attention when shapes allow (TPU-compiled,
        # interpreted on CPU); "blockwise"/"pallas" force a path
        "attn_impl": "auto",
        # lax.scan unroll factors — the V=32k roofline attributes ~27 % of
        # the step to while self-time (ROOFLINE_transformer_32k.json), all
        # of it the fused-loss chunk scans in this base model (the trunk
        # is a Python-loop Sequential, not a scan).  loss_unroll lets XLA
        # software-pipeline the loss chunks; layers_unroll applies ONLY to
        # PipelineTransformerLM's stacked-layer scan.  1 = the r4 behavior.
        "layers_unroll": 1,
        "loss_unroll": 1,
        # "stream" (or any stream_sources/stream_dir config) switches the
        # data plane to the checkpointable multi-source token stream
        # (models/data/stream.py); default stays the PTB-style chopped set
        "dataset": "ptb",
    }

    def build_data(self):
        cfg = self.config
        if (cfg.get("dataset") == "stream" or cfg.get("stream_sources")
                or cfg.get("stream_dir")):
            from theanompi_tpu.models.data.stream import StreamTokenDataset

            if cfg.get("stream_dir") and not cfg.get("stream_sources"):
                import os

                root = cfg["stream_dir"]
                cfg = dict(cfg)
                cfg["stream_sources"] = [
                    {"name": d, "path": os.path.join(root, d)}
                    for d in sorted(os.listdir(root))
                    if os.path.isdir(os.path.join(root, d))
                ]
            return StreamTokenDataset(cfg)
        return PTBData(self.config)

    def _make_block(self) -> L.Layer:
        """Block factory hook — MoE variant overrides with :class:`_MoEBlock`."""
        cfg = self.config
        return _Block(cfg["dim"], cfg["heads"], dropout=cfg["dropout"],
                      attn_impl=cfg["attn_impl"])

    def build_net(self):
        """The TRUNK only (embed … final LN).  The LM head lives outside the
        Sequential so the loss can fuse the head matmul into a chunked
        cross entropy (``ops.losses.fused_lm_xent``) instead of
        materializing ``[B, T, V]`` fp32 logits — ruinous at real vocab.

        **Checkpoint format break** (documented per ADVICE r3 #3): the head
        moved from the Sequential's trailing Dense (leaf
        ``NN_dense/{w,b}``) to a top-level ``head`` key, so transformer
        checkpoints written before this change no longer restore.  No shim
        is kept — prior-round checkpoints were test artifacts, and the
        restore fails loudly (``KeyError: 'head/w'``) rather than silently
        mismapping."""
        cfg = self.config
        layers: list[L.Layer] = [
            L.Embedding(self.data.vocab, cfg["dim"],
                        w_init=init_lib.normal(0.02)),
            PositionEmbedding(cfg["seq_len"], cfg["dim"]),
        ]
        for _ in range(cfg["n_layers"]):
            layers.append(self._make_block())
        layers.append(L.LayerNorm())
        self._head = L.Dense(self.data.vocab, w_init=init_lib.glorot_normal)
        return L.Sequential(layers), (cfg["seq_len"],)

    def init_params(self, rng):
        k_trunk, k_head = jax.random.split(rng)
        params, state, out_shape = self.net.init(k_trunk, self.in_shape)
        self._out_shape = out_shape
        head_p, _, _ = self._head.init(k_head, out_shape)
        # flat Sequential tree + a top-level "head" key: TP rules and tests
        # address trunk leaves by their Sequential names unchanged
        params["head"] = head_p
        return params, state

    def apply_trunk(self, params, state, x, *, train, rng):
        """-> (hidden states [B, T, D], new_state); variants (pipeline)
        override this, keeping head+loss in one shared path."""
        trunk = {k: v for k, v in params.items() if k != "head"}
        return self.net.apply(trunk, state, x, train=train, rng=rng)

    def fused_loss_enabled(self) -> bool:
        mode = self.config.get("fused_loss", "auto")
        if mode == "auto":
            return self.data.vocab >= 8192
        return bool(mode)

    # -- serving path (ISSUE 6) ----------------------------------------------
    def _serving_layers(self):
        """(name, layer) pairs of the trunk Sequential, in order — the
        serving engine drives the SAME param tree the trainer checkpoints,
        so a verified restore plugs straight in."""
        if self.net is None or not hasattr(self.net, "layers"):
            raise NotImplementedError(
                f"{type(self).__name__} has no serving decode path (the "
                f"pipeline variant stacks its blocks for GPipe; export the "
                f"checkpoint to the plain TransformerLM layout to serve it)")
        return [(f"{i:02d}_{layer.name}", layer)
                for i, layer in enumerate(self.net.layers)]

    def _head_logits(self, cp, h):
        y = quant.matmul_any(h, cp["head"]["w"])
        if "b" in cp["head"]:
            y = y + cp["head"]["b"].astype(h.dtype)
        return y.astype(jnp.float32)

    def apply_logits(self, params, state, tokens):
        """Full-sequence forward to per-position logits ``[B, T, V]`` —
        the batched reference the serving parity/smoke tests compare
        incremental decode against (and the plain eval entry point the
        fused loss path deliberately avoids materializing in training)."""
        cp = self.precision.cast_to_compute(params)
        h, _ = self.apply_trunk(cp, state, tokens, train=False, rng=None)
        return self._head_logits(cp, h)

    def apply_prefill(self, params, state, kv_cache, table_row, tokens):
        """Prompt prefill for ONE sequence: ``tokens`` ``[1, P_pad]`` (end-
        padded to a whole number of cache blocks — causal masking keeps the
        padding out of every real position's context), ``table_row`` the
        sequence's block table.  -> (logits ``[1, P_pad, V]`` fp32, cache').
        """
        del state
        cp = self.precision.cast_to_compute(params)
        x, li = None, 0
        for name, layer in self._serving_layers():
            p = cp.get(name, {})
            if isinstance(layer, L.Embedding):
                x = jnp.take(p["w"], tokens, axis=0)
            elif isinstance(layer, PositionEmbedding):
                x, _ = layer.apply(p, {}, x)
            elif isinstance(layer, _Block):
                x, kv_cache = layer.prefill_step(p, x, kv_cache, li,
                                                 table_row)
                li += 1
            else:
                x, _ = layer.apply(p, {}, x)
        return self._head_logits(cp, x), kv_cache

    def apply_prefill_partial(self, params, state, kv_cache, suffix_row,
                              full_row, tokens, prefix_len):
        """Partial prefill (ISSUE 17): forward ONLY the uncached suffix of
        one sequence — ``tokens`` ``[1, S_pad]`` are the prompt's tokens
        from absolute position ``prefix_len`` on (end-padded to whole
        cache blocks) — while attending over the cached-prefix blocks the
        radix cache matched into ``full_row``.  ``suffix_row`` names the
        fresh blocks the suffix K/V lands in.  -> (logits ``[1, S_pad, V]``
        fp32, cache').

        Position embeddings index at ``prefix_len + s`` (clipped into the
        table for end-padding positions, whose lanes are masked garbage by
        the same causal contract as full prefill's end-padding)."""
        del state
        cp = self.precision.cast_to_compute(params)
        x, li = None, 0
        for name, layer in self._serving_layers():
            p = cp.get(name, {})
            if isinstance(layer, L.Embedding):
                x = jnp.take(p["w"], tokens, axis=0)
            elif isinstance(layer, PositionEmbedding):
                idx = jnp.clip(prefix_len + jnp.arange(tokens.shape[1]),
                               0, p["pos"].shape[0] - 1)
                pos = jnp.take(p["pos"], idx, axis=0).astype(x.dtype)
                x = x + pos[None]
            elif isinstance(layer, _Block):
                x, kv_cache = layer.prefill_suffix_step(
                    p, x, kv_cache, li, suffix_row, full_row, prefix_len)
                li += 1
            else:
                x, _ = layer.apply(p, {}, x)
        return self._head_logits(cp, x), kv_cache

    def apply_decode(self, params, state, kv_cache, positions, tokens):
        """One incremental decode step for a fixed batch of sequences:
        ``tokens`` ``[B]`` (the token AT ``positions``), ``positions``
        ``[B]`` 0-based.  Appends each layer's K/V to the paged cache and
        attends over the cached context.  -> (logits ``[B, V]`` fp32,
        cache').  Inactive batch slots ride along with their block tables
        pointed at the cache's reserved null block."""
        del state
        # the is_leaf fence keeps the precision policy out of int8
        # QuantizedTensor leaves (their fp32 scales must not cast to the
        # compute dtype) — the serving fast path feeds them through here
        # to the fused matmul kernel (ISSUE 18)
        cp = self.precision.cast_to_compute(
            params, is_leaf=lambda x: isinstance(x, quant.QuantizedTensor))
        x, li = None, 0
        for name, layer in self._serving_layers():
            p = cp.get(name, {})
            if isinstance(layer, L.Embedding):
                x = jnp.take(p["w"], tokens, axis=0)[:, None, :]
            elif isinstance(layer, PositionEmbedding):
                pos = jnp.take(p["pos"], positions, axis=0).astype(x.dtype)
                x = x + pos[:, None, :]
            elif isinstance(layer, _Block):
                x, kv_cache = layer.decode_step(p, x, kv_cache, li,
                                                positions)
                li += 1
            else:
                x, _ = layer.apply(p, {}, x)
        return self._head_logits(cp, x[:, 0, :]), kv_cache

    # -- sharding ------------------------------------------------------------
    def _head_specs(self, params):
        """Head placement: vocab-parallel (Megatron parallel CE) whenever
        the fused loss is on — w ``P(None, model)``, b ``P(model)`` — so
        under TP no rank ever sees more than ``[chunk, V/tp]`` scores.  On
        a size-1 model axis this degrades to replicated, and the plain
        fused/naive paths read the full head."""
        from theanompi_tpu.parallel.mesh import MODEL_AXIS

        if not self.fused_loss_enabled():
            return jax.tree.map(lambda _: P(), params["head"])
        specs = {"w": P(None, MODEL_AXIS)}
        if "b" in params["head"]:
            specs["b"] = P(MODEL_AXIS)
        return specs

    def param_specs(self, params):
        specs = specs_from_rules(params, TP_RULES)
        specs["head"] = self._head_specs(params)
        return specs

    def batch_partition(self) -> P:
        if self.config["seq_parallel"]:
            return P(DATA_AXIS, SEQ_AXIS)
        return P(DATA_AXIS)

    def grad_reduce_axes(self) -> tuple[str, ...]:
        if self.config["seq_parallel"]:
            return (DATA_AXIS, SEQ_AXIS)
        return (DATA_AXIS,)

    def loss_fn(self, params, state, batch, rng, train: bool):
        from theanompi_tpu.ops.losses import fused_lm_xent, fused_lm_xent_vp
        from theanompi_tpu.parallel.mesh import MODEL_AXIS
        from theanompi_tpu.parallel.tensor import axis_bound

        from theanompi_tpu.ops import softmax_cross_entropy, top_k_error

        cp = self.precision.cast_to_compute(params)
        h, new_state = self.apply_trunk(cp, state, batch["x"],
                                        train=train, rng=rng)
        w, b = cp["head"]["w"], cp["head"].get("b")
        if self.fused_loss_enabled():
            unroll = int(self.config.get("loss_unroll", 1) or 1)
            if axis_bound(MODEL_AXIS) and jax.lax.axis_size(MODEL_AXIS) > 1:
                # w/b are this shard's vocab slice (see _head_specs)
                loss, err1, err5 = fused_lm_xent_vp(h, w, b, batch["y"],
                                                    MODEL_AXIS,
                                                    unroll=unroll)
            else:
                loss, err1, err5 = fused_lm_xent(h, w, b, batch["y"],
                                                 unroll=unroll)
        else:
            logits, _ = self._head.apply(cp["head"], {}, h)
            loss = softmax_cross_entropy(logits, batch["y"])
            err1 = top_k_error(logits, batch["y"], k=1)
            err5 = (top_k_error(logits, batch["y"], k=5)
                    if logits.shape[-1] >= 5 else jnp.zeros((), jnp.float32))
        if self.config.get("l2", 0.0):
            loss = loss + self.config["l2"] * self.l2_sq_norm(params)
        metrics = {"cost": loss, "error": err1, "error_top5": err5,
                   "perplexity": jnp.exp(loss)}
        return loss, (new_state, metrics)


class MoETransformerLM(TransformerLM):
    """Mixture-of-experts LM: dp × tp × **ep** (SURVEY.md-beyond).

    Every block's FFN is a switch-routed :class:`~theanompi_tpu.ops.moe
    .MoEFFN` with ``n_experts`` global experts sharded over the ``model``
    mesh axis (expert parallelism shares the axis with the attention's
    tensor parallelism — the standard pairing).  The Switch load-balance
    auxiliary loss joins the training objective at ``moe_aux_weight``.
    """

    default_config = {
        **TransformerLM.default_config,
        "n_experts": 8,
        "capacity_factor": 1.25,
        "moe_aux_weight": 0.01,
    }

    def _make_block(self) -> L.Layer:
        cfg = self.config
        return _MoEBlock(
            cfg["dim"], cfg["heads"], dropout=cfg["dropout"],
            attn_impl=cfg["attn_impl"], n_experts=cfg["n_experts"],
            capacity_factor=cfg["capacity_factor"],
        )

    def param_specs(self, params):
        from theanompi_tpu.parallel.mesh import MODEL_AXIS

        base = specs_from_rules(params, TP_RULES)
        expert_keys = ("up_w", "up_b", "down_w", "down_b")

        def walk(p_sub, b_sub, in_moe, key):
            if isinstance(p_sub, dict):
                return {k: walk(p_sub[k], b_sub[k], in_moe or k == "moe", k)
                        for k in p_sub}
            if in_moe and key in expert_keys:
                return P(MODEL_AXIS)  # stacked experts shard dim 0
            return b_sub

        return walk(params, base, False, "")

    def loss_fn(self, params, state, batch, rng, train: bool):
        import jax.tree_util as jtu

        loss, (new_state, metrics) = super().loss_fn(
            params, state, batch, rng, train
        )
        auxes = [
            leaf for path, leaf in jtu.tree_flatten_with_path(new_state)[0]
            if getattr(path[-1], "key", None) == "aux"
        ]
        if auxes:
            a = sum(auxes) / len(auxes)
            metrics = {**metrics, "moe_aux": a}
            if train:
                loss = loss + self.config["moe_aux_weight"] * a
        return loss, (new_state, metrics)


class PipelineTransformerLM(TransformerLM):
    """Pipeline-parallel variant: dp × pp × tp (SURVEY.md-beyond, scale
    contract — the composition a real pod LM run needs).

    The ``n_layers`` blocks are *stacked* — every block-param leaf carries a
    leading ``[n_layers, ...]`` axis sharded over the ``pipe`` mesh axis —
    and the forward runs the GPipe collective-permute schedule
    (:func:`theanompi_tpu.parallel.pipeline.pipeline_apply`) with
    ``n_micro`` microbatches.  Embedding/positions/final-LN/head are
    replicated; their cross-pipe gradient correctness comes from the
    pinned-VJP collectives inside ``pipeline_apply``.  With pipe size 1
    (or no mesh) this is numerically the plain stacked transformer.

    **Tensor parallelism composes structurally**: the stacked block leaves
    keep their Megatron column/row specs over ``model`` BEHIND the leading
    ``pipe`` axis (``P(pipe, None, model)`` on a stacked column-parallel
    weight), so inside ``shard_map`` each device holds its pipe-stage's
    slice of its tp-shard, and the blocks' f/g collectives psum over
    ``model`` within every pipe rank exactly as in the unstacked model.
    The two pinned-VJP families compose because they act on disjoint axes:
    pipeline's f/g pin ``pipe`` (stage-0 injection / last-stage output),
    Megatron's f/g pin ``model`` (column inputs / row outputs) — each
    collective is an identity over the other's axis.

    **Sequence parallelism composes too** (VERDICT r3 #5 lifted the old
    refusal): ring attention's ppermutes ride the ``seq`` axis only and the
    GPipe schedule's ride ``pipe`` only, and because every device traces the
    SAME SPMD program, each pipeline schedule step runs the full KV ring
    (and, in reverse, the full backward ring) in lockstep across seq peers
    at every pipe rank — there is no cross-axis hop interleaving to get
    wrong.  The ring's custom VJP pins ``seq`` (dk/dv land home after a
    full lap), pipeline's pins ``pipe``, Megatron's pins ``model``: three
    disjoint-axis families.  Verified by the pp2×sp2 ≡ single-device
    multi-step test (``tests/test_pipeline.py``).
    """

    default_config = {
        **TransformerLM.default_config,
        "n_micro": 4,       # microbatches per step (must divide batch_size)
        "seq_parallel": False,
    }

    def build_net(self):
        cfg = self.config
        t, d = cfg["seq_len"], cfg["dim"]
        self._block = _Block(cfg["dim"], cfg["heads"], cfg["dropout"],
                             attn_impl=cfg["attn_impl"])
        self._embed = L.Embedding(self.data.vocab, d,
                                  w_init=init_lib.normal(0.02))
        self._pos = PositionEmbedding(t, d)
        self._ln_f = L.LayerNorm()
        self._head = L.Dense(self.data.vocab, w_init=init_lib.glorot_normal)
        return None, (t,)

    def init_params(self, rng):
        cfg = self.config
        t, d = cfg["seq_len"], cfg["dim"]
        k_embed, k_pos, k_blocks, k_ln, k_head = jax.random.split(rng, 5)
        pe, _, _ = self._embed.init(k_embed, (t,))
        pp, _, _ = self._pos.init(k_pos, (t, d))
        block_keys = jax.random.split(k_blocks, cfg["n_layers"])

        def one(k):
            p, _, _ = self._block.init(k, (t, d))
            return p

        stacked = jax.vmap(one)(block_keys)  # leaves [n_layers, ...]
        pl_, _, _ = self._ln_f.init(k_ln, (t, d))
        ph, _, _ = self._head.init(k_head, (t, d))
        return {"embed": pe, "pos": pp, "blocks": stacked,
                "ln_f": pl_, "head": ph}, {}

    def param_specs(self, params):
        from theanompi_tpu.parallel.mesh import PIPE_AXIS

        # stacked block leaves shard their leading stage axis over `pipe`;
        # behind it each leaf keeps its Megatron spec over `model` (rule
        # paths are matched as "blocks/attn/q/w" etc., same regexes as the
        # unstacked model)
        tp = specs_from_rules({"blocks": params["blocks"]}, TP_RULES)["blocks"]
        stacked = jax.tree.map(
            lambda spec: P(PIPE_AXIS, *spec),
            tp, is_leaf=lambda x: isinstance(x, P),
        )
        return {
            "embed": jax.tree.map(lambda _: P(), params["embed"]),
            "pos": jax.tree.map(lambda _: P(), params["pos"]),
            "blocks": stacked,
            "ln_f": jax.tree.map(lambda _: P(), params["ln_f"]),
            # vocab-parallel under tp when the fused loss is on
            "head": self._head_specs(params),
        }

    def apply_trunk(self, params, state, x, *, train, rng):
        """The pipelined forward up to the final LN; head+loss stay in the
        shared ``loss_fn`` path (l2 over the pipe-sharded blocks is handled
        by the spec-aware ``l2_sq_norm``)."""
        from theanompi_tpu.parallel.pipeline import pipeline_apply
        from theanompi_tpu.parallel.tensor import axis_bound

        cfg = self.config
        # tensor AND sequence parallelism compose (disjoint pinned-VJP
        # axes — see class docstring); the blocks' ring attention runs its
        # seq-axis KV laps inside every GPipe schedule step
        emb, _ = self._embed.apply(params["embed"], {}, x)
        emb, _ = self._pos.apply(params["pos"], {}, emb)

        def stage_fn(chunk, act, t):
            if rng is None:
                key0 = None
            else:
                key0 = jax.random.fold_in(rng, t)
                if axis_bound("pipe"):
                    key0 = jax.random.fold_in(
                        key0, jax.lax.axis_index("pipe"))

            def one(carry, bp):
                a, key = carry
                kb = None
                if key is not None:
                    key = jax.random.fold_in(key, 7)
                    kb = key
                y, _ = self._block.apply(bp, {}, a, train=train, rng=kb)
                return (y, key), None

            (act, _), _ = jax.lax.scan(
                one, (act, key0), chunk,
                unroll=int(cfg.get("layers_unroll", 1) or 1))
            return act

        h = pipeline_apply(stage_fn, params["blocks"], emb, cfg["n_micro"])
        h, _ = self._ln_f.apply(params["ln_f"], {}, h)
        return h, state
