"""DCGAN / WGAN under the rule framework (BASELINE.md config 5).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/dcgan.py`` /
``wgan.py`` — fork additions per BASELINE.json; Radford et al. 2015 DCGAN
(strided-conv D, transposed-conv G, Adam lr=2e-4 β1=0.5) and Arjovsky et al.
2017 WGAN (critic, weight clipping, RMSProp lr=5e-5), trained as a
two-optimizer loop inside the data-parallel rules.

The rules drive this model through :meth:`make_custom_step`: one compiled
step updates the discriminator on (real, fake) then the generator through
the frozen discriminator; under BSP both gradient sets are exchanged with
the rule's collective, so GAN training data-parallelizes exactly like a
classifier.  ``config["wgan"]=True`` switches losses, adds critic weight
clipping, and runs ``n_critic`` critic steps per generator step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from theanompi_tpu.models.contract import Model
from theanompi_tpu.models.data.cifar10 import Cifar10Data
from theanompi_tpu.ops import layers as L
from theanompi_tpu.ops.initializers import normal
from theanompi_tpu.ops.losses import sigmoid_binary_cross_entropy
from theanompi_tpu.ops.opt import Adam, RMSProp
from theanompi_tpu.parallel.exchanger import EXCHANGE_RNG_TAG
from theanompi_tpu.parallel.mesh import DATA_AXIS, replica_rng


class DCGAN(Model):
    """Generator/discriminator pair on CIFAR-10-shaped images."""

    default_config = {
        "batch_size": 64,
        "n_epochs": 25,
        "lr": 2e-4,
        "z_dim": 100,
        "gen_base": 128,    # channels at the 4x4 stage
        "disc_base": 64,
        "image_size": 32,
        "wgan": False,
        "clip": 0.01,       # WGAN critic weight clip
        "n_critic": 5,      # WGAN critic steps per generator step
        # two-timescale update rule (TTUR, Heusel et al. 2017): the
        # discriminator trains at lr * disc_lr_scale.  At small scales a
        # matched-capacity D saturates before G learns; slowing D (rather
        # than shrinking it) keeps the game balanced without handicapping
        # D's capacity
        "disc_lr_scale": 1.0,
        "augment": False,   # GAN training uses raw images
        "normalize": "tanh",  # reals in [-1,1], matching the tanh generator
    }

    def __init__(self, config=None):
        super().__init__(config)
        s = self.config["image_size"]
        if s % 8 != 0:
            raise ValueError(f"image_size must be divisible by 8, got {s}")
        self.gen, self.disc = self._build_pair()

    def build_data(self):
        return Cifar10Data(self.config)

    def build_optimizer(self):
        if self.config["wgan"]:
            return RMSProp()  # WGAN paper
        return Adam(b1=0.5)   # DCGAN paper

    def adjust_hyperp(self, epoch: int) -> float:
        del epoch
        return self.config.get("lr", 5e-5 if self.config["wgan"] else 2e-4)

    # -- nets ----------------------------------------------------------------
    def _build_pair(self):
        cfg = self.config
        gb, db = cfg["gen_base"], cfg["disc_base"]
        s4 = cfg["image_size"] // 8  # spatial size at the deepest stage
        w02 = normal(0.02)           # DCGAN-paper init
        gen = L.Sequential((
            L.Dense(s4 * s4 * gb * 2, w_init=w02),
            _Reshape((s4, s4, gb * 2)),
            L.BatchNorm(),
            L.Activation("relu"),
            L.ConvTranspose2D(gb, 4, stride=2, w_init=w02, use_bias=False),
            L.BatchNorm(),
            L.Activation("relu"),
            L.ConvTranspose2D(gb // 2, 4, stride=2, w_init=w02, use_bias=False),
            L.BatchNorm(),
            L.Activation("relu"),
            L.ConvTranspose2D(3, 4, stride=2, w_init=w02),
            L.Activation("tanh"),
        ))
        disc = L.Sequential((
            L.Conv2D(db, 4, stride=2, w_init=w02),
            L.Activation("leaky_relu"),
            L.Conv2D(db * 2, 4, stride=2, w_init=w02, use_bias=False),
            L.BatchNorm(),
            L.Activation("leaky_relu"),
            L.Conv2D(db * 4, 4, stride=2, w_init=w02, use_bias=False),
            L.BatchNorm(),
            L.Activation("leaky_relu"),
            L.Flatten(),
            L.Dense(1, w_init=w02),
        ))
        return gen, disc

    # -- contract ------------------------------------------------------------
    def init_opt_state(self, optimizer, params):
        return {
            "gen": optimizer.init(params["gen"]),
            "disc": optimizer.init(params["disc"]),
        }

    def opt_state_specs(self, optimizer, param_specs):
        return {
            "gen": optimizer.init_specs(param_specs["gen"]),
            "disc": optimizer.init_specs(param_specs["disc"]),
        }

    def init_params(self, rng):
        kg, kd = jax.random.split(rng)
        cfg = self.config
        s = cfg["image_size"]
        gp, gs, _ = self.gen.init(kg, (cfg["z_dim"],))
        dp, ds, _ = self.disc.init(kd, (s, s, 3))
        return {"gen": gp, "disc": dp}, {"gen": gs, "disc": ds}

    def _sample(self, gen_params, gen_state, z, train):
        x, new_gs = self.gen.apply(gen_params, gen_state, z, train=train)
        return x, new_gs

    def _d_loss(self, disc_params, disc_state, real, fake, train):
        wgan = self.config["wgan"]
        s_real, ns = self.disc.apply(disc_params, disc_state, real, train=train)
        s_fake, ns = self.disc.apply(disc_params, ns, fake, train=train)
        if wgan:
            loss = jnp.mean(s_fake) - jnp.mean(s_real)  # critic maximizes gap
        else:
            loss = sigmoid_binary_cross_entropy(
                s_real, jnp.ones_like(s_real)
            ) + sigmoid_binary_cross_entropy(s_fake, jnp.zeros_like(s_fake))
        return loss, ns

    def _g_loss(self, gen_params, states, disc_params, z, train):
        fake, new_gs = self._sample(gen_params, states["gen"], z, train)
        s_fake, new_ds = self.disc.apply(disc_params, states["disc"], fake, train=train)
        if self.config["wgan"]:
            loss = -jnp.mean(s_fake)
        else:
            loss = sigmoid_binary_cross_entropy(s_fake, jnp.ones_like(s_fake))
        return loss, (new_gs, new_ds)

    def loss_fn(self, params, state, batch, rng, train: bool):
        """Eval path for validate(): discriminator loss on (val-real, fake)."""
        key = rng if rng is not None else jax.random.PRNGKey(0)
        kz, _ = jax.random.split(key)
        real = batch["x"].astype(self.precision.compute_dtype)
        z = jax.random.normal(
            kz, (real.shape[0], self.config["z_dim"]), real.dtype
        )
        cp = self.precision.cast_to_compute(params)
        fake, _ = self._sample(cp["gen"], state["gen"], z, train=False)
        d_loss, _ = self._d_loss(cp["disc"], state["disc"], real, fake, False)
        return d_loss, (state, {"cost": d_loss})

    # -- the two-optimizer compiled step -------------------------------------
    def make_custom_step(self, opt, base_key, exchanger=None):
        cfg = self.config
        wgan = cfg["wgan"]
        clip = cfg["clip"]

        def exchange(g, key):
            # the per-step key matters: ring_int8 seeds its stochastic
            # rounding from it — a fixed fallback key would repeat the same
            # per-element rounding direction every step (systematic drift)
            return exchanger.exchange(g, rng=key) if exchanger is not None \
                else g

        def inner(params, state, opt_state, batch, lr, step):
            rng = replica_rng(jax.random.fold_in(base_key, step), DATA_AXIS)
            exch_key = jax.random.fold_in(rng, EXCHANGE_RNG_TAG)
            kz1, kz2 = jax.random.split(rng)
            real = batch["x"].astype(self.precision.compute_dtype)
            b = real.shape[0]
            cast = self.precision.cast_to_compute

            # discriminator/critic step (generator frozen)
            z = jax.random.normal(kz1, (b, cfg["z_dim"]), real.dtype)
            fake, gen_state = self._sample(
                cast(params["gen"]), state["gen"], z, train=True
            )
            fake = lax_stop(fake)

            def d_obj(dp):
                loss, ns = self._d_loss(cast(dp), state["disc"], real, fake, True)
                return loss, ns

            (d_loss, disc_state), d_grads = jax.value_and_grad(
                d_obj, has_aux=True
            )(params["disc"])
            d_grads = exchange(d_grads, jax.random.fold_in(exch_key, 0))
            new_disc, new_dopt = opt.update(
                d_grads, opt_state["disc"], params["disc"],
                lr * cfg["disc_lr_scale"]
            )
            if wgan:
                new_disc = jax.tree.map(
                    lambda p: jnp.clip(p, -clip, clip), new_disc
                )

            # generator step through the (frozen) updated discriminator
            z2 = jax.random.normal(kz2, (b, cfg["z_dim"]), real.dtype)

            def g_obj(gp):
                loss, (gs, _) = self._g_loss(
                    cast(gp), {"gen": gen_state, "disc": disc_state},
                    cast(new_disc), z2, True,
                )
                return loss, gs

            (g_loss, gen_state2), g_grads = jax.value_and_grad(
                g_obj, has_aux=True
            )(params["gen"])
            g_grads = exchange(g_grads, jax.random.fold_in(exch_key, 1))
            new_gen, new_gopt = opt.update(
                g_grads, opt_state["gen"], params["gen"], lr
            )
            if wgan:
                # generator updates only every n_critic-th step; gate params
                # AND optimizer state so its schedule matches the reference
                # (zeroed-grad updates would still decay RMSProp's sq buffer)
                do_g = jnp.equal(jnp.mod(step, cfg["n_critic"]), 0)
                keep = lambda new, old: jax.tree.map(
                    lambda a, b: jnp.where(do_g, a, b), new, old
                )
                new_gen = keep(new_gen, params["gen"])
                new_gopt = keep(new_gopt, opt_state["gen"])

            new_params = {"gen": new_gen, "disc": new_disc}
            new_state = {"gen": gen_state2, "disc": disc_state}
            new_opt = {"gen": new_gopt, "disc": new_dopt}
            metrics = {
                "cost": d_loss + g_loss,
                "d_loss": d_loss,
                "g_loss": g_loss,
            }
            return new_params, new_state, new_opt, metrics

        return inner


def lax_stop(x):
    return jax.lax.stop_gradient(x)


class _Reshape(L.Layer):
    """Reshape trailing dims (generator stem: dense → spatial map)."""

    def __init__(self, shape):
        self.target = tuple(shape)

    def init(self, key, in_shape):
        del key
        import numpy as np

        if int(np.prod(in_shape)) != int(np.prod(self.target)):
            raise ValueError(f"cannot reshape {in_shape} -> {self.target}")
        return {}, {}, self.target

    def apply(self, params, state, x, *, train=False, rng=None):
        return x.reshape(x.shape[0], *self.target), state


class WGAN(DCGAN):
    """WGAN as its own class for import-by-string parity."""

    default_config = {**DCGAN.default_config, "wgan": True, "lr": 5e-5}
