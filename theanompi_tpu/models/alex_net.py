"""AlexNet (BASELINE.md config 2 — the 8-worker BSP scaling model).

Reference (unverified — SURVEY.md §2.1): ``theanompi/models/alex_net.py``,
descended from the Ding et al. ``theano_alexnet`` 1-GPU port: 5 conv layers
(LRN after conv1/conv2), 3 max-pools, two dropout FC-4096 layers, softmax
over 1000 classes; trained with momentum SGD, step LR decay, and the
paper-era crop+mirror augmentation (supplied here by
:mod:`theanompi_tpu.models.data.imagenet`).

Config ``lrn=False`` drops the LRN layers (they predate BN and cost HBM
bandwidth; off they let XLA fuse conv+relu+pool cleanly) — default on, for
parity with the reference.  Config ``grouped=True`` uses the original
2-group conv2/4/5 (Krizhevsky 2012's two-GPU split, kept by the
``theano_alexnet`` lineage); default off — on one TPU chip the split buys
nothing and halves the MXU tile width, but the knob preserves the exact
historical architecture (param count drops to ~58M from ~61M).
"""

from __future__ import annotations

from theanompi_tpu.models.contract import SupervisedModel
from theanompi_tpu.models.data.imagenet import ImageNetData
from theanompi_tpu.ops import initializers as init_lib
from theanompi_tpu.ops import layers as L


class AlexNet(SupervisedModel):
    default_config = {
        "batch_size": 128,
        "n_epochs": 70,
        "lr": 0.01,
        "lr_decay_epochs": (20, 40, 60),
        "lr_decay_factor": 0.1,
        "momentum": 0.9,
        "weight_decay": 5e-4,
        "image_size": 224,
        "n_classes": 1000,
        "lrn": True,
        "dropout": 0.5,
        "grouped": False,  # 2-group conv2/4/5 (Krizhevsky two-GPU split)
    }

    def build_data(self):
        return ImageNetData(self.config)

    def build_net(self):
        cfg = self.config
        maybe_lrn = [L.LRN(size=5)] if cfg["lrn"] else []
        g = 2 if cfg["grouped"] else 1
        layers: list[L.Layer] = [
            L.Conv2D(96, 11, stride=4, padding=2),
            L.Activation("relu"),
            *maybe_lrn,
            L.MaxPool(3, stride=2),
            L.Conv2D(256, 5, padding=2, groups=g),
            L.Activation("relu"),
            *maybe_lrn,
            L.MaxPool(3, stride=2),
            L.Conv2D(384, 3, padding=1),
            L.Activation("relu"),
            L.Conv2D(384, 3, padding=1, groups=g),
            L.Activation("relu"),
            L.Conv2D(256, 3, padding=1, groups=g),
            L.Activation("relu"),
            L.MaxPool(3, stride=2),
            L.Flatten(),
            L.Dense(4096),
            L.Activation("relu"),
            L.Dropout(cfg["dropout"]),
            L.Dense(4096),
            L.Activation("relu"),
            L.Dropout(cfg["dropout"]),
            L.Dense(cfg["n_classes"], w_init=init_lib.glorot_normal),
        ]
        s = cfg["image_size"]
        return L.Sequential(layers), (s, s, 3)
