"""``python -m theanompi_tpu.fleet`` == ``tmfleet``."""

from theanompi_tpu.fleet.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
