"""Job specs, runtime records, and the priority queue (ISSUE 11).

A job is one supervised training session.  Everything it owns lives
under ``<fleet_dir>/jobs/<id>/`` — checkpoint dir, telemetry dir, the
supervisor's ``resilience.json``, and the crash-safe ``job.json``
runtime record — so concurrent children never share mutable state, and
a fleet dir survives a scheduler restart with every job's lifecycle
intact.

The spec carries the launch intent (rule, model, config, device range,
priority); the record carries where the job is in its lifecycle::

    queued -> running -> done | failed
                 |  ^
                 v  |            (priority preemption: SIGTERM -> exit 75
           preempting            with a cadence checkpoint + data cursor,
                 |               then an elastic relaunch on whatever
                 v               devices remain: --resume --resume-reshard)
             preempted -> queued'
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")

#: every lifecycle state a ``job.json`` may carry
STATUSES = ("queued", "running", "preempting", "preempted", "done",
            "failed")
TERMINAL = ("done", "failed")


class JobSpecError(ValueError):
    """A job that cannot be scheduled as asked (the config-error class:
    ``tmfleet`` maps it to exit 78, and it is never retried)."""


@dataclasses.dataclass
class JobSpec:
    """The submit-time half of a job: what to run and what it needs."""

    job_id: str
    priority: int = 0
    min_devices: int = 1            #: gang size floor (all-or-nothing)
    max_devices: int | None = None  #: cap; None = take whatever is free
    #: "training" (a tmlauncher child — preemptible, resumes elastically)
    #: or "serving" (a tmserve replica driven off a durable queue file,
    #: ISSUE 19 — never a preemption victim: replicas leave through the
    #: router's drain, and a SIGTERM-drained replica exiting 0 is DONE,
    #: not requeued)
    kind: str = "training"
    rule: str = "BSP"
    modelfile: str = "theanompi_tpu.models.wide_resnet"
    modelclass: str = "WideResNet"
    model_config: dict = dataclasses.field(default_factory=dict)
    rule_config: dict = dataclasses.field(default_factory=dict)
    env: dict = dataclasses.field(default_factory=dict)
    extra_args: list = dataclasses.field(default_factory=list)
    max_restarts: int = 3
    backoff_base: float = 0.1
    #: test seam: an explicit child argv replaces the launcher command
    #: entirely (scheduler unit tests run ``python -c`` children with no
    #: jax import; such a job manages its own resume semantics)
    argv: list | None = None

    def validate(self) -> None:
        if not isinstance(self.job_id, str) or not _ID_RE.match(self.job_id):
            raise JobSpecError(
                f"invalid job id {self.job_id!r} (letters, digits, "
                f"'.', '_', '-'; must not start with a separator)")
        if int(self.min_devices) < 1:
            raise JobSpecError(
                f"job {self.job_id!r}: min_devices must be >= 1, "
                f"got {self.min_devices}")
        if (self.max_devices is not None
                and int(self.max_devices) < int(self.min_devices)):
            raise JobSpecError(
                f"job {self.job_id!r}: max_devices {self.max_devices} < "
                f"min_devices {self.min_devices}")
        if self.kind not in ("training", "serving"):
            raise JobSpecError(
                f"job {self.job_id!r}: unknown kind {self.kind!r} "
                f"(training | serving)")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        names = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - names)
        if unknown:
            raise JobSpecError(f"unknown job-spec keys {unknown}")
        return cls(**d)


@dataclasses.dataclass
class JobRecord:
    """The runtime half: spec + lifecycle, persisted as ``job.json``."""

    spec: JobSpec
    status: str = "queued"
    devices: int | None = None     #: current lease (None when not running)
    preemptions: int = 0
    episodes: int = 0
    last_exit: int | None = None
    #: exit code of each preempted episode, in order — durable witness
    #: that victims left cooperatively (75 = cadence checkpoint written),
    #: since ``last_exit`` is overwritten by the resumed episode
    preempt_exits: list = dataclasses.field(default_factory=list)
    #: why the job failed (ISSUE 13): supervisor classification plus the
    #: final attempt's flight-recorder blackbox summary / health verdicts
    #: when the child left them; None until the job fails
    failure_cause: dict | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "JobRecord":
        d = dict(d)
        spec = JobSpec.from_dict(d.pop("spec"))
        if d.get("status") not in STATUSES:
            raise JobSpecError(f"unknown job status {d.get('status')!r}")
        return cls(spec=spec, **d)


def job_dir(fleet_dir: str, job_id: str) -> str:
    return os.path.join(fleet_dir, "jobs", job_id)


def write_record(fleet_dir: str, rec: JobRecord) -> str:
    """Atomic ``job.json`` publish (same tmp+replace pattern as every
    other artifact in the tree)."""
    jdir = job_dir(fleet_dir, rec.spec.job_id)
    os.makedirs(jdir, exist_ok=True)
    path = os.path.join(jdir, "job.json")
    with open(path + ".tmp", "w") as f:
        json.dump(rec.to_dict(), f, indent=1)
    os.replace(path + ".tmp", path)
    return path


def read_record(fleet_dir: str, job_id: str) -> JobRecord:
    with open(os.path.join(job_dir(fleet_dir, job_id), "job.json")) as f:
        return JobRecord.from_dict(json.load(f))


def list_records(fleet_dir: str) -> list[JobRecord]:
    """Every persisted job record in the fleet dir, by job id."""
    root = os.path.join(fleet_dir, "jobs")
    if not os.path.isdir(root):
        return []
    out = []
    for jid in sorted(os.listdir(root)):
        if os.path.isfile(os.path.join(root, jid, "job.json")):
            out.append(read_record(fleet_dir, jid))
    return out


def build_child_cmd(spec: JobSpec, devices: int, jdir: str, *,
                    resume: bool = False) -> list[str]:
    """The child argv for one episode of ``spec`` gang-scheduled onto
    ``devices`` workers.  Config values round-trip through the
    launcher's ``--set`` literal grammar via ``repr`` (``'fp32'`` stays
    a string, ``4`` an int).  ``resume=True`` is the elastic relaunch
    after a preemption: ``--resume --resume-reshard`` replans the
    cadence checkpoint onto the new device count, and the sample cursor
    (PR 9) fast-forwards the data stream — nothing replayed or skipped
    across the shrink.

    ``kind="serving"`` (ISSUE 19) builds a ``tmserve --queue-file`` child
    instead: the replica tails ``<jdir>/queue.jsonl`` for router-appended
    requests and logs terminal states to ``<jdir>/REQUESTS.jsonl``.
    ``resume`` is meaningless for a replica — restart continuity is the
    REQUESTS.jsonl dedup, not a checkpoint (both command strings are just
    module names; fleet never imports launcher or serving)."""
    if spec.argv is not None:
        return list(spec.argv)
    if spec.kind == "serving":
        cmd = [sys.executable, "-m", "theanompi_tpu.serving",
               "--modelfile", spec.modelfile,
               "--modelclass", spec.modelclass]
        for k, v in spec.model_config.items():
            cmd += ["--set", f"{k}={v!r}"]
        cmd += ["--queue-file", os.path.join(jdir, "queue.jsonl"),
                "--requests-log", os.path.join(jdir, "REQUESTS.jsonl"),
                "--telemetry-dir", os.path.join(jdir, "telemetry"),
                "--quiet"]
        cmd += [str(a) for a in spec.extra_args]
        return cmd
    cmd = [sys.executable, "-m", "theanompi_tpu.launcher",
           "--rule", spec.rule, "--devices", str(int(devices)),
           "--modelfile", spec.modelfile, "--modelclass", spec.modelclass]
    for k, v in spec.model_config.items():
        cmd += ["--set", f"{k}={v!r}"]
    for k, v in spec.rule_config.items():
        cmd += ["--rule-set", f"{k}={v!r}"]
    cmd += ["--checkpoint-dir", os.path.join(jdir, "ckpt"), "--quiet"]
    cmd += [str(a) for a in spec.extra_args]
    if resume:
        cmd += ["--resume", "--resume-reshard"]
    return cmd


class JobQueue:
    """Runnable specs, highest priority first, FIFO within a band.

    Preempted jobs re-enter through :meth:`push` and keep their original
    submit sequence, so a requeued victim does not jump peers that were
    already waiting at its priority.
    """

    def __init__(self):
        self._seq = 0
        self._items: list[tuple[int, int, JobSpec]] = []
        self._seqs: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, job_id: str) -> bool:
        return any(s.job_id == job_id for _, _, s in self._items)

    def push(self, spec: JobSpec) -> None:
        spec.validate()
        if spec.job_id in self:
            raise JobSpecError(f"job {spec.job_id!r} is already queued")
        seq = self._seqs.setdefault(spec.job_id, self._seq)
        self._seq = max(self._seq, seq + 1)
        self._items.append((-int(spec.priority), seq, spec))

    def ordered(self) -> list[JobSpec]:
        """Snapshot in scheduling order (does not consume)."""
        return [s for _, _, s in sorted(self._items,
                                        key=lambda t: (t[0], t[1]))]

    def remove(self, job_id: str) -> None:
        self._items = [t for t in self._items if t[2].job_id != job_id]
