"""Device-pool ledger: inventory + per-job gang leases (ISSUE 11).

The fleet's single source of truth for "who holds which devices".  The
pool is an integer inventory (TPU slices hand out chips by count, and
the launcher's ``--devices N`` operand is how a child claims them), the
leases are per-job counts, and allocation is **all-or-nothing gang
allocation** — a training job steps collectively across every worker, so
a partial grant would deadlock it at the first collective.

State is crash-safe JSON in the fleet dir with a two-generation publish:
every persist atomically rotates the live file to ``ledger.json.prev``
before the new generation replaces ``ledger.json``, so a torn main file
(power cut mid-publish; rehearsed by the ``fleet:ledger_torn_write``
fault) recovers from the previous generation instead of crashing the
scheduler.  Pool discovery reuses the elastic supervisor's probe seam
(:func:`~theanompi_tpu.resilience.supervisor.probe_device_count`,
ISSUE 8) when no explicit size is given.
"""

from __future__ import annotations

import json
import os
import sys

from theanompi_tpu.resilience.supervisor import probe_device_count


class LedgerError(RuntimeError):
    """The pool state is unusable (no size, impossible lease, torn state
    with no recoverable generation)."""


#: failure-history bounds (ISSUE 19 satellite): a router restarting
#: replicas for days must not grow ledger.json without bound, so the
#: ledger keeps the last FAILURES_PER_JOB causes per job across the
#: FAILURES_JOBS most-recently-failing jobs, with dropped-count
#: witnesses for everything evicted
FAILURES_PER_JOB = 3
FAILURES_JOBS = 32


class DeviceLedger:
    """Device inventory with per-job leases and crash-safe persistence.

    ``pool_size=None`` re-opens a persisted ledger (the size is part of
    the state) or, for a fresh fleet dir, probes the live inventory.
    ``fault_plan`` wires the ``fleet:ledger_torn_write@idx`` site: the
    persist at ordinal ``idx`` tears the just-published main file in
    half, exactly what a power cut mid-publish leaves behind.
    """

    def __init__(self, fleet_dir: str, pool_size: int | None = None, *,
                 fault_plan=None, probe_env: dict | None = None):
        os.makedirs(fleet_dir, exist_ok=True)
        self.path = os.path.join(fleet_dir, "ledger.json")
        self.fault_plan = fault_plan
        self._persists = 0
        state = self._load()
        if state is not None:
            self.pool_size = int(state["pool_size"])
            self.leases = {str(k): int(v)
                           for k, v in state["leases"].items()}
            # optional (ISSUE 13) — a pre-13 ledger has no failures map;
            # pre-19 entries were one bare cause dict per job — wrap them
            # into the bounded shape ({"causes": [...], "dropped", "seq"})
            self.failures = {
                str(k): (v if isinstance(v, dict) and "causes" in v
                         else {"causes": [v], "dropped": 0, "seq": 0})
                for k, v in (state.get("failures") or {}).items()}
            self.failures_dropped = int(state.get("failures_dropped", 0))
            self._fail_seq = 1 + max(
                (int(v.get("seq", 0)) for v in self.failures.values()),
                default=-1)
            if pool_size is not None and int(pool_size) != self.pool_size:
                raise LedgerError(
                    f"--pool-size {pool_size} conflicts with the persisted "
                    f"ledger's {self.pool_size} ({self.path}); remove the "
                    f"ledger to re-inventory the pool")
        else:
            if pool_size is None:
                pool_size = probe_device_count(probe_env, log=self._log)
            if pool_size is None or int(pool_size) < 1:
                raise LedgerError(
                    "cannot size the device pool: no explicit pool size, "
                    "no persisted ledger, and the device probe failed")
            self.pool_size = int(pool_size)
            self.leases: dict[str, int] = {}
            self.failures: dict[str, dict] = {}
            self.failures_dropped = 0
            self._fail_seq = 0
            self.persist()

    # -- leases --------------------------------------------------------------
    @property
    def free(self) -> int:
        return self.pool_size - sum(self.leases.values())

    def lease_of(self, job_id: str) -> int:
        return self.leases.get(job_id, 0)

    def alloc(self, job_id: str, n: int) -> bool:
        """All-or-nothing gang allocation: lease exactly ``n`` devices to
        ``job_id`` and persist, or change nothing and return False."""
        n = int(n)
        if n < 1 or n > self.pool_size:
            raise LedgerError(
                f"job {job_id!r} asked for {n} device(s) from a pool "
                f"of {self.pool_size}")
        if job_id in self.leases:
            raise LedgerError(f"job {job_id!r} already holds a lease "
                              f"({self.leases[job_id]} device(s))")
        if n > self.free:
            return False
        self.leases[job_id] = n
        self.persist()
        return True

    def release(self, job_id: str) -> int:
        """Drop ``job_id``'s lease; -> how many devices came free (0 when
        it held none — releasing twice is not an error: the episode
        thread and a crash-recovery sweep may race benignly)."""
        freed = self.leases.pop(job_id, 0)
        if freed:
            self.persist()
        return freed

    def record_failure(self, job_id: str, cause: dict) -> None:
        """Persist ``job_id``'s failure cause (ISSUE 13): the supervisor
        classification plus the blackbox summary the dead child left, so
        ``tmfleet status`` of a long-gone job still answers *why*.

        Bounded (ISSUE 19 satellite): each job keeps its last
        ``FAILURES_PER_JOB`` causes with a per-job ``dropped`` count, and
        only the ``FAILURES_JOBS`` most-recently-failing jobs stay in the
        map at all (``failures_dropped`` witnesses whole-job evictions) —
        a crash-looping replica restarted for days cannot grow
        ledger.json without bound."""
        entry = self.failures.setdefault(
            str(job_id), {"causes": [], "dropped": 0, "seq": 0})
        entry["causes"].append(dict(cause))
        if len(entry["causes"]) > FAILURES_PER_JOB:
            entry["dropped"] += len(entry["causes"]) - FAILURES_PER_JOB
            entry["causes"] = entry["causes"][-FAILURES_PER_JOB:]
        entry["seq"] = self._fail_seq
        self._fail_seq += 1
        while len(self.failures) > FAILURES_JOBS:
            oldest = min(self.failures,
                         key=lambda k: int(self.failures[k].get("seq", 0)))
            self.failures.pop(oldest)
            self.failures_dropped += 1
        self.persist()

    def last_failure(self, job_id: str) -> dict | None:
        """The most recent recorded cause for ``job_id`` (None when its
        history was never recorded or has been evicted)."""
        entry = self.failures.get(str(job_id))
        if not entry or not entry.get("causes"):
            return None
        return entry["causes"][-1]

    # -- crash-safe persistence ----------------------------------------------
    def persist(self) -> None:
        data = {"version": 1, "pool_size": self.pool_size,
                "leases": dict(sorted(self.leases.items())),
                "generation": self._persists}
        if self.failures:
            data["failures"] = dict(sorted(self.failures.items()))
        if self.failures_dropped:
            data["failures_dropped"] = self.failures_dropped
        with open(self.path + ".tmp", "w") as f:
            json.dump(data, f, indent=1)
        if os.path.exists(self.path):
            # rotate BEFORE the new generation lands: a crash between the
            # two renames leaves .prev whole, which _load falls back to
            os.replace(self.path, self.path + ".prev")
        os.replace(self.path + ".tmp", self.path)
        ordinal = self._persists
        self._persists += 1
        if self.fault_plan is not None and self.fault_plan.fire(
                "fleet", ordinal, action="ledger_torn_write") is not None:
            self._log(f"injected torn write on persist {ordinal}")
            with open(self.path, "r+b") as f:
                f.truncate(max(1, os.path.getsize(self.path) // 2))

    def _load(self) -> dict | None:
        """The persisted state, falling back one generation on a torn
        main file; None when no generation exists (fresh pool)."""
        torn: Exception | None = None
        for path in (self.path, self.path + ".prev"):
            try:
                with open(path) as f:
                    state = json.load(f)
                if "pool_size" not in state or "leases" not in state:
                    raise LedgerError(f"{path} is missing required keys")
            except FileNotFoundError:
                continue
            except (ValueError, LedgerError) as e:
                torn = e
                continue
            if torn is not None:
                self._log(f"recovered pool state from {path} "
                          f"(main generation torn: {torn})")
            return state
        if torn is not None:
            raise LedgerError(
                f"every ledger generation is unreadable: {torn}")
        return None

    @staticmethod
    def _log(msg: str) -> None:
        print(f"fleet: ledger: {msg}", file=sys.stderr, flush=True)
