"""``tmfleet`` — submit jobs to a fleet dir and run the scheduler.

Shares ``tmlauncher``'s operational contract: the same ``--set key=value``
literal grammar (``ast.literal_eval`` with bare-string fallback) and the
same typed exit codes — config errors (bad spec, bad fault plan, torn
ledger with no recoverable generation) exit
:data:`~theanompi_tpu.resilience.codes.EXIT_CONFIG`, anything unexpected
exits :data:`~theanompi_tpu.resilience.codes.EXIT_CRASH`, and ``run``
returns the scheduler's own verdict (clean only when every job
completed).  The grammar is restated locally rather than imported: the
fleet layer supervises the launcher as a *subprocess* and must never
import it (the ``tmlint`` import-DAG wall enforces this).

::

    tmfleet submit --fleet-dir /pool --job-id a --priority 0 \\
        --set depth=16 --set n_epochs=2
    tmfleet submit --fleet-dir /pool --job-id b --priority 5 \\
        --min-devices 4 --max-devices 4
    tmfleet run --fleet-dir /pool --pool-size 8
    tmfleet status --fleet-dir /pool
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys

from theanompi_tpu.resilience import EXIT_CLEAN, EXIT_CONFIG, EXIT_CRASH
from theanompi_tpu.resilience.faults import FaultPlanError
from theanompi_tpu.fleet.jobs import (
    JobRecord,
    JobSpec,
    JobSpecError,
    list_records,
    write_record,
)
from theanompi_tpu.fleet.ledger import LedgerError


def _parse_kv(pairs: list[str] | None) -> dict:
    """``key=value`` pairs with Python-literal values, bare strings kept
    as strings — the same grammar as ``tmlauncher --set`` (restated here;
    the layering wall forbids importing the launcher)."""
    out = {}
    for pair in pairs or []:
        if "=" not in pair:
            raise SystemExit(f"expected key=value, got {pair!r}")
        k, v = pair.split("=", 1)
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmfleet", allow_abbrev=False,
        description="multi-job fleet orchestration on the elastic "
                    "supervisor")
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("submit", allow_abbrev=False,
                        help="queue one job spec into the fleet dir")
    ps.add_argument("--fleet-dir", required=True)
    ps.add_argument("--job-id", required=True)
    ps.add_argument("--priority", type=int, default=0)
    ps.add_argument("--min-devices", type=int, default=1)
    ps.add_argument("--max-devices", type=int, default=None)
    ps.add_argument("--rule", default="BSP")
    ps.add_argument("--modelfile",
                    default="theanompi_tpu.models.wide_resnet")
    ps.add_argument("--modelclass", default="WideResNet")
    ps.add_argument("--set", action="append", dest="overrides",
                    metavar="KEY=VALUE",
                    help="model config override (Python literal values)")
    ps.add_argument("--rule-set", action="append", dest="rule_overrides",
                    metavar="KEY=VALUE")
    ps.add_argument("--extra-arg", action="append", dest="extra_args",
                    metavar="ARG",
                    help="verbatim extra launcher argv for the child "
                         "(repeatable; e.g. --extra-arg "
                         "--compile-cache-dir=/cache)")
    ps.add_argument("--max-restarts", type=int, default=3)
    ps.add_argument("--backoff-base", type=float, default=0.1)

    pr = sub.add_parser("run", allow_abbrev=False,
                        help="run the scheduler until every job is done")
    pr.add_argument("--fleet-dir", required=True)
    pr.add_argument("--pool-size", type=int, default=None,
                    help="device inventory (default: probe, or the "
                         "persisted ledger's)")
    pr.add_argument("--poll-s", type=float, default=0.05)
    pr.add_argument("--fault-plan", default=None,
                    help="fleet-site fault plan (NOT read from the env; "
                         "children are always scrubbed)")
    pr.add_argument("--quiet", action="store_true",
                    help="suppress the final status JSON on stdout")

    pt = sub.add_parser("status", allow_abbrev=False,
                        help="print the fleet's job + pool state as JSON")
    pt.add_argument("--fleet-dir", required=True)
    return p


def _status_dict(fleet_dir: str) -> dict:
    jobs = [r.to_dict() for r in list_records(fleet_dir)]
    pool = None
    path = os.path.join(fleet_dir, "ledger.json")
    for p in (path, path + ".prev"):
        try:
            with open(p) as f:
                pool = json.load(f)
            break
        except (FileNotFoundError, ValueError):
            continue
    return {"jobs": jobs, "pool": pool}


def _cmd_submit(args) -> int:
    spec = JobSpec(
        job_id=args.job_id, priority=args.priority,
        min_devices=args.min_devices, max_devices=args.max_devices,
        rule=args.rule, modelfile=args.modelfile,
        modelclass=args.modelclass,
        model_config=_parse_kv(args.overrides),
        rule_config=_parse_kv(args.rule_overrides),
        extra_args=list(args.extra_args or []),
        max_restarts=args.max_restarts, backoff_base=args.backoff_base)
    spec.validate()
    jpath = os.path.join(args.fleet_dir, "jobs", spec.job_id, "job.json")
    if os.path.exists(jpath):
        raise JobSpecError(f"job {spec.job_id!r} already exists "
                           f"in {args.fleet_dir}")
    write_record(args.fleet_dir, JobRecord(spec=spec))
    print(f"tmfleet: queued {spec.job_id!r} (priority {spec.priority}, "
          f"devices {spec.min_devices}..{spec.max_devices or 'free'})")
    return EXIT_CLEAN


def _cmd_run(args) -> int:
    from theanompi_tpu.fleet.scheduler import FleetScheduler

    sched = FleetScheduler(args.fleet_dir, args.pool_size,
                           fault_plan=args.fault_plan, poll_s=args.poll_s)
    for rec in list_records(args.fleet_dir):
        if rec.status not in ("done", "failed"):
            sched.adopt(rec)
    rc = sched.run()
    if not args.quiet:
        print(json.dumps(_status_dict(args.fleet_dir), indent=1))
    return rc


def _cmd_status(args) -> int:
    print(json.dumps(_status_dict(args.fleet_dir), indent=1))
    return EXIT_CLEAN


def _error_line(phase: str, e: BaseException) -> None:
    print(f"tmfleet: error: {phase}: {type(e).__name__}: {e}",
          file=sys.stderr)
    if os.environ.get("THEANOMPI_DEBUG"):
        import traceback

        traceback.print_exc()


def main(argv: list[str] | None = None) -> int:
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)
    try:
        if args.cmd == "submit":
            return _cmd_submit(args)
        if args.cmd == "run":
            return _cmd_run(args)
        return _cmd_status(args)
    except (JobSpecError, LedgerError, FaultPlanError) as e:
        _error_line("config", e)
        return EXIT_CONFIG
    except Exception as e:
        _error_line("fleet", e)
        return EXIT_CRASH


if __name__ == "__main__":
    raise SystemExit(main())
