"""Multi-job fleet orchestration on the elastic supervisor (ISSUE 11).

One device pool, many queued training jobs: the :class:`DeviceLedger`
gang-leases devices all-or-nothing, the :class:`FleetScheduler` places
queued :class:`JobSpec`\\ s in priority order as supervised children
(the shared :func:`~theanompi_tpu.resilience.supervisor.run_job` seam),
and priority contention is resolved by *elastic preemption*: the victim
SIGTERMs out with a cadence checkpoint + data cursor (exit 75) and later
resumes on whatever devices remain via ``--resume --resume-reshard`` —
bit-equal params, gap-free data stream.

The package imports only resilience/telemetry/utils (the ``tmlint``
import DAG holds the wall): training and serving machinery is always a
*subprocess*, never an import.
"""

from theanompi_tpu.fleet.jobs import (
    STATUSES,
    JobQueue,
    JobRecord,
    JobSpec,
    JobSpecError,
    build_child_cmd,
    job_dir,
    list_records,
    read_record,
    write_record,
)
from theanompi_tpu.fleet.ledger import DeviceLedger, LedgerError
from theanompi_tpu.fleet.scheduler import FleetScheduler, read_fleet_events

__all__ = [
    "STATUSES",
    "DeviceLedger",
    "FleetScheduler",
    "JobQueue",
    "JobRecord",
    "JobSpec",
    "JobSpecError",
    "LedgerError",
    "build_child_cmd",
    "job_dir",
    "list_records",
    "read_fleet_events",
    "read_record",
    "write_record",
]
