"""Gang scheduler: queued jobs onto free devices, with priority
preemption and elastic resume (ISSUE 11 tentpole).

One scheduler owns one fleet dir.  Each scheduling pass walks the queue
in priority order and gang-allocates the head job from the ledger; when
the pool cannot fit it, lower-priority running jobs are preempted —
SIGTERMed through their :class:`~theanompi_tpu.resilience.supervisor.
Supervisor`, whose child checkpoints at the preemption cadence and exits
75 — and later resume **elastically** on whatever devices remain
(``--resume --resume-reshard``; the PR 9 sample cursor keeps the data
stream gap-free across the shrink, nothing replayed or skipped).

Every job runs as a supervised child via the shared
:func:`~theanompi_tpu.resilience.supervisor.run_job` seam — the exact
per-attempt run/classify/backoff loop behind ``tmlauncher --supervise``
— so a crash inside an episode is the *supervisor's* problem (restart in
place, same lease); the fleet only sees episode boundaries.  Lifecycle
decisions land twice: a ``fleet_events.jsonl`` audit line and a
telemetry instant through the registered
:data:`~theanompi_tpu.telemetry.metrics.FLEET_INSTANTS` names.
"""

from __future__ import annotations

import json
import os
import threading
import time

from theanompi_tpu.analysis.interleave import sp
from theanompi_tpu.resilience import EXIT_CLEAN, EXIT_CRASH
from theanompi_tpu.resilience.faults import FaultPlan
from theanompi_tpu.resilience.supervisor import run_job
from theanompi_tpu.fleet.jobs import (
    TERMINAL,
    JobQueue,
    JobRecord,
    JobSpec,
    JobSpecError,
    build_child_cmd,
    job_dir,
    read_record,
    write_record,
)
from theanompi_tpu.fleet.ledger import DeviceLedger
from theanompi_tpu.telemetry.health import hung_verdict, read_health


def read_fleet_events(fleet_dir: str) -> list[dict]:
    """The fleet's audit log, one dict per lifecycle decision."""
    path = os.path.join(fleet_dir, "fleet_events.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class FleetScheduler:
    """Multi-job gang scheduler over one device pool.

    ``fault_plan`` (string or :class:`FaultPlan`) wires the two ``fleet``
    sites — ``kill_job@idx`` SIGKILLs the idx-th *launched* child (the
    job's supervisor restarts it in place), ``ledger_torn_write@idx``
    tears the idx-th ledger persist.  Deliberately NOT read from
    ``THEANOMPI_FAULT_PLAN``: that env var is for training processes,
    and the scheduler scrubs it from every child env so a plan aimed at
    the fleet never detonates inside a job (and vice versa).
    """

    def __init__(self, fleet_dir: str, pool_size: int | None = None, *,
                 fault_plan: "str | FaultPlan | None" = None,
                 poll_s: float = 0.05, env: dict | None = None,
                 telemetry: bool = True, probe_env: dict | None = None):
        self.fleet_dir = fleet_dir
        os.makedirs(fleet_dir, exist_ok=True)
        if isinstance(fault_plan, str):
            fault_plan = FaultPlan.parse(fault_plan)
        self.fault_plan = fault_plan
        self.ledger = DeviceLedger(fleet_dir, pool_size,
                                   fault_plan=fault_plan,
                                   probe_env=probe_env)
        self.poll_s = float(poll_s)
        self.env = dict(env) if env else {}
        self._lock = threading.RLock()
        self.queue = JobQueue()
        self.records: dict[str, JobRecord] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._sups: dict[str, object] = {}
        self._launches = 0
        self._episode_wall: dict[str, float] = {}  #: launch wall time
        self._hung_flagged: set[str] = set()
        #: jobs asked to drain before their episode thread registered a
        #: Supervisor (the on_sup race — mirrors the "preempting" check)
        self._draining: set[str] = set()
        self._next_health_s = 0.0
        self._telemetry = None
        self._telemetry_enabled = bool(telemetry)
        self.events_path = os.path.join(fleet_dir, "fleet_events.jsonl")

    # -- submission & adoption ------------------------------------------------
    def submit(self, spec: JobSpec) -> JobRecord:
        """Queue ``spec`` and persist its record; scheduling happens on
        the run loop's next pass."""
        spec.validate()
        with self._lock:
            if spec.min_devices > self.ledger.pool_size:
                raise JobSpecError(
                    f"job {spec.job_id!r} needs min_devices="
                    f"{spec.min_devices} but the pool has only "
                    f"{self.ledger.pool_size}")
            if spec.job_id in self.records:
                raise JobSpecError(
                    f"job {spec.job_id!r} already exists in this fleet")
            rec = JobRecord(spec=spec)
            self.records[spec.job_id] = rec
            self.queue.push(spec)
            write_record(self.fleet_dir, rec)
            return rec

    def adopt(self, rec: JobRecord) -> None:
        """Re-own a persisted record from a dead scheduler.  A job that
        was mid-flight when that scheduler died left a cadence
        checkpoint behind, so it re-enters as ``preempted`` and resumes
        elastically like any preemption victim."""
        sp("fleet.adopt")
        with self._lock:
            if rec.spec.job_id in self.records:
                raise JobSpecError(
                    f"job {rec.spec.job_id!r} already exists in this fleet")
            if rec.status in ("running", "preempting"):
                rec.status = "preempted"
                rec.devices = None
                write_record(self.fleet_dir, rec)
            self.records[rec.spec.job_id] = rec
            if rec.status in ("queued", "preempted"):
                self.queue.push(rec.spec)
            # stale leases from the dead scheduler's ledger generation
            self.ledger.release(rec.spec.job_id)

    # -- events ---------------------------------------------------------------
    def _event(self, name: str, **fields) -> None:
        line = {"ts": time.time(),  # lint: wall-ok — audit log stamp
                "event": name, **fields}
        # lint: atomic-publish-ok — JSONL audit log; readers tolerate a
        # torn final line (json.loads per line, bad tail skipped)
        with open(self.events_path, "a") as f:
            f.write(json.dumps(line) + "\n")
        if self._telemetry is not None:
            self._telemetry.instant(name, **fields)

    # -- the run loop ---------------------------------------------------------
    def run(self) -> int:
        """Schedule until every submitted job is terminal; -> EXIT_CLEAN
        when all jobs completed, EXIT_CRASH when any failed."""
        if self._telemetry_enabled and self._telemetry is None:
            from theanompi_tpu.telemetry import Telemetry

            self._telemetry = Telemetry(
                os.path.join(self.fleet_dir, "telemetry"), rank=0)
        try:
            while True:
                with self._lock:
                    self._reap()
                    self._adopt_new()
                    self._schedule_pass()
                    self._health_pass()
                    if self.records and all(
                            r.status in TERMINAL
                            for r in self.records.values()):
                        break
                    if not self.records:
                        break
                time.sleep(self.poll_s)
        finally:
            for t in list(self._threads.values()):
                t.join()
            if self._telemetry is not None:
                self._telemetry.close()
                self._telemetry = None
        failed = [j for j, r in self.records.items() if r.status == "failed"]
        return EXIT_CRASH if failed else EXIT_CLEAN

    def _reap(self) -> None:
        for jid, t in list(self._threads.items()):
            if not t.is_alive():
                t.join()
                del self._threads[jid]
                self._sups.pop(jid, None)

    def _adopt_new(self) -> None:
        """Pick up ``queued`` records another process published into the
        fleet dir since the last pass — the live half of ``tmfleet
        submit`` (the BASELINE step-8 flow: a high-priority job submitted
        while ``tmfleet run`` owns the pool must contend NOW, not on the
        next scheduler start).  Only fresh ``queued`` records qualify;
        anything mid-lifecycle belongs to startup adoption."""
        root = os.path.join(self.fleet_dir, "jobs")
        try:
            found = sorted(os.listdir(root))
        except OSError:
            return
        for jid in found:
            if jid in self.records:
                continue
            try:
                rec = read_record(self.fleet_dir, jid)
            except (OSError, ValueError):
                continue  # no job.json yet, or a foreign dir entry
            if rec.status != "queued":
                continue
            try:
                self.submit(rec.spec)
            except JobSpecError as e:
                # an unschedulable live submit must not wedge the loop:
                # mark it failed on disk so `tmfleet status` shows why
                rec.status = "failed"
                write_record(self.fleet_dir, rec)
                self.records[jid] = rec
                self._event("fleet.fail", job=jid, exit_code=None,
                            cause=f"config: {e}")

    def _schedule_pass(self) -> None:
        """One pass: place the highest-priority queued job, preempting
        strictly-lower-priority running jobs when the free pool cannot
        fit its gang.  Strict priority order — an unschedulable head
        blocks the pass (no backfill past it), so a big high-priority
        job cannot be starved by a stream of small ones."""
        sp("fleet.pass")
        for spec in self.queue.ordered():
            rec = self.records[spec.job_id]
            n_min = int(spec.min_devices)
            if self.ledger.free >= n_min:
                n = (self.ledger.free if spec.max_devices is None
                     else min(int(spec.max_devices), self.ledger.free))
                self.queue.remove(spec.job_id)
                self._launch(rec, n)
                continue
            # devices already draining toward us?
            pending = sum(r.devices or 0 for r in self.records.values()
                          if r.status == "preempting")
            if self.ledger.free + pending >= n_min:
                break  # wait for the drain, don't double-preempt
            victims = sorted(
                (r for r in self.records.values()
                 if r.status == "running"
                 and r.spec.priority < spec.priority
                 # only TRAINING yields to priority (ISSUE 19): it
                 # checkpoints and resumes elastically; a serving replica
                 # holds live traffic and leaves only through the
                 # router's drain (drain_job), never a forced preemption
                 and r.spec.kind == "training"),
                key=lambda r: (r.spec.priority, r.spec.job_id))
            avail = self.ledger.free + pending
            for victim in victims:
                if avail >= n_min:
                    break
                avail += victim.devices or 0
                self._preempt(victim, for_job=spec.job_id)
            break  # head job owns the pass until it launches

    def _health_pass(self) -> None:
        """Surface fresh critical hang verdicts from running jobs'
        ``HEALTH.json`` as ``fleet.hang`` audit events (ISSUE 13).

        The actual preempt-and-restart is the job's own supervisor's
        move — it watches the same file and kills the wedged child
        instead of waiting out its lease/hang-timeout; the fleet's role
        is the audit trail.  Emitted once per hang episode (cleared when
        the verdict clears or the episode ends), gated on the file
        postdating this episode's launch so a previous episode's dying
        verdict is not re-reported."""
        now = time.perf_counter()
        if now < self._next_health_s:
            return
        self._next_health_s = now + 0.5
        for jid, rec in self.records.items():
            if rec.status != "running":
                continue
            tdir = os.path.join(job_dir(self.fleet_dir, jid), "telemetry")
            health = read_health(tdir)
            hung = None
            launched = self._episode_wall.get(jid, float("inf"))
            if health is not None and float(
                    health.get("updated", 0.0)) >= launched:
                hung = hung_verdict(health)
            if hung is not None and jid not in self._hung_flagged:
                self._hung_flagged.add(jid)
                self._event("fleet.hang", job=jid,
                            reason=hung.get("reason"),
                            step=health.get("steps"))
            elif hung is None:
                self._hung_flagged.discard(jid)

    def _launch(self, rec: JobRecord, n: int) -> None:
        jid = rec.spec.job_id
        if not self.ledger.alloc(jid, n):
            # raced a release between the free check and here; requeue
            self.queue.push(rec.spec)
            return
        resume = rec.status == "preempted"
        rec.status = "running"
        rec.devices = n
        rec.episodes += 1
        write_record(self.fleet_dir, rec)
        self._event("fleet.resume" if resume else "fleet.schedule",
                    job=jid, devices=n, priority=rec.spec.priority)
        # lint: wall-ok — gates HEALTH.json freshness by its wall stamp
        self._episode_wall[jid] = time.time()
        kill_child = (
            self.fault_plan is not None
            and self.fault_plan.fire("fleet", self._launches,
                                     action="kill_job") is not None)
        self._launches += 1
        t = threading.Thread(
            target=self._episode, args=(rec, n, resume, kill_child),
            name=f"fleet-{jid}", daemon=True)
        self._threads[jid] = t
        t.start()

    def _preempt(self, rec: JobRecord, *, for_job: str) -> None:
        jid = rec.spec.job_id
        rec.status = "preempting"
        write_record(self.fleet_dir, rec)
        self._event("fleet.preempt", job=jid, victim_of=for_job)
        sup = self._sups.get(jid)
        if sup is not None:
            sup.terminate()
        # else: the episode thread has not registered its Supervisor yet;
        # its on_supervisor callback sees status == "preempting" and
        # terminates immediately (no lost preemption).

    def drain_job(self, job_id: str) -> bool:
        """Ask a running serving replica to drain and exit clean (the
        router's scale-down path, ISSUE 19): SIGTERM through its
        supervisor — the replica stops admitting, finishes in-flight
        work within its ``--drain-s``, exits 0 and the episode
        classifies DONE (lease released, chips back in the pool).  ->
        whether a running job was signalled."""
        with self._lock:
            rec = self.records.get(job_id)
            if rec is None or rec.status != "running":
                return False
            self._event("fleet.drain", job=job_id)
            self._draining.add(job_id)
            sup = self._sups.get(job_id)
            if sup is not None:
                sup.terminate()
            # else: the on_sup race — the episode thread's callback sees
            # the _draining mark and terminates immediately
            return True

    # -- one supervised episode (worker thread) -------------------------------
    def _episode(self, rec: JobRecord, n: int, resume: bool,
                 kill_child: bool) -> None:
        jid = rec.spec.job_id
        jdir = job_dir(self.fleet_dir, jid)
        cmd = build_child_cmd(rec.spec, n, jdir, resume=resume)
        # scrub the scheduler's own fault plan; a plan aimed at the fleet
        # must never detonate inside a training child
        env = {"THEANOMPI_FAULT_PLAN": ""}
        env.update(self.env)
        env.update(rec.spec.env)

        def on_sup(sup):
            with self._lock:
                self._sups[jid] = sup
                preempting = (rec.status == "preempting"
                              or jid in self._draining)
            if preempting:
                sup.terminate()
            if kill_child:
                threading.Thread(target=self._kill_when_up, args=(sup,),
                                 name=f"fleet-kill-{jid}",
                                 daemon=True).start()

        serving = rec.spec.kind == "serving"
        result = run_job(
            cmd, on_supervisor=on_sup,
            max_restarts=rec.spec.max_restarts,
            backoff_base=rec.spec.backoff_base,
            resilience_path=os.path.join(jdir, "resilience.json"),
            telemetry_dir=os.path.join(jdir, "telemetry"),
            env=env,
            # a restarted replica's continuity is REQUESTS.jsonl dedup,
            # not a checkpoint — never append training resume flags
            **({"resume_args": ()} if serving else {}))
        sp("fleet.episode.done")
        with self._lock:
            self.ledger.release(jid)
            self._episode_wall.pop(jid, None)
            self._hung_flagged.discard(jid)
            self._draining.discard(jid)
            rec.devices = None
            rec.last_exit = result.exit_code
            if result.preempted and not serving:
                rec.status = "preempted"
                rec.preemptions += 1
                rec.preempt_exits.append(result.exit_code)
                self.queue.push(rec.spec)
            elif result.clean:
                rec.status = "done"
                self._event("fleet.complete", job=jid,
                            exit_code=result.exit_code)
            else:
                rec.status = "failed"
                # ISSUE 13: the durable answer to "why did it fail" —
                # supervisor classification + the final attempt's
                # blackbox/health harvest (supervisor already mtime-gated
                # them into the attempt record) — lands on the job record
                # AND in the ledger's failures map
                cause = {"cause": result.cause,
                         "exit_code": result.exit_code}
                last = result.attempts[-1] if result.attempts else {}
                for k in ("blackbox", "health"):
                    if k in last:
                        cause[k] = last[k]
                rec.failure_cause = cause
                self.ledger.record_failure(jid, cause)
                self._event("fleet.fail", job=jid,
                            exit_code=result.exit_code, cause=result.cause,
                            blackbox=bool(last.get("blackbox")))
            write_record(self.fleet_dir, rec)

    @staticmethod
    def _kill_when_up(sup) -> None:
        """fleet:kill_job delivery: SIGKILL the supervised child as soon
        as its process exists (the supervisor then classifies a crash
        and restarts it in place — the fleet sees one episode)."""
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            p = getattr(sup, "_proc", None)
            if p is not None:
                try:
                    p.kill()
                except OSError:  # lint: swallow-ok — child already gone
                    pass
                return
            time.sleep(0.01)
