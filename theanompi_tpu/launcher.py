"""tmlauncher: the CLI session launcher.

Reference (unverified — SURVEY.md §1/§3.1): ``tmlauncher``/``launch_session.py``
composed an ``mpirun`` command line placing one worker process per requested
``cudaN`` device (plus the EASGD server rank) and joined it.

TPU-native re-expression: there is no process tree to compose — the "cluster"
is the device mesh.  The launcher parses the same launch intent
(rule, device count, modelfile/modelclass, config) and drives
``Rule.init(...).wait()`` in-process.  On a multi-host pod, run this same
command on every host under the JAX multi-controller runtime
(``jax.distributed.initialize`` is called automatically when the standard TPU
pod environment variables are present); each host sees the global mesh.

Examples::

    tmlauncher --rule BSP --devices 8 \
        --modelfile theanompi_tpu.models.resnet50 --modelclass ResNet50 \
        --set batch_size=64 --set n_epochs=90 \
        --rule-set exch_strategy=psum_bf16 --record-dir ./record

    tmlauncher --rule EASGD --devices all --rule-set tau=8 \
        --checkpoint-dir ./ckpt --resume
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
import time


class DistributedInitError(RuntimeError):
    """The pod's multi-controller runtime could not be joined (after
    retries) — a hard error, because training single-host while the other
    hosts wait at a collective would hang the whole slice."""


def _parse_kv(pairs: list[str]) -> dict:
    """k=v pairs with Python-literal values (`lr=0.1`, `lrn=False`,
    `stage_blocks=(3,4,6,3)`); bare strings stay strings."""
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"expected key=value, got {pair!r}")
        k, v = pair.split("=", 1)
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def _maybe_init_distributed(retries: int | None = None,
                            backoff_base: float | None = None,
                            sleep=time.sleep) -> None:
    """Join the JAX multi-controller runtime on a pod (no-op on one host).

    ISSUE 4 satellite: a flaky coordinator used to be swallowed here,
    silently downgrading a pod launch to single-host training.  Now init
    is retried with bounded exponential backoff
    (``THEANOMPI_DIST_INIT_RETRIES`` / ``THEANOMPI_DIST_INIT_BACKOFF``,
    defaults 3 / 1s), and exhausting the retries while the pod env vars
    are present raises :class:`DistributedInitError` — the supervisor
    classifies that as a restartable crash, never a quiet downgrade.
    An "already initialized" runtime (harness-managed) still short-circuits.
    """
    if not (os.environ.get("TPU_WORKER_HOSTNAMES")
            or os.environ.get("JAX_COORDINATOR_ADDRESS")):
        return
    import jax

    if retries is None:
        retries = int(os.environ.get("THEANOMPI_DIST_INIT_RETRIES", "3"))
    if backoff_base is None:
        backoff_base = float(os.environ.get("THEANOMPI_DIST_INIT_BACKOFF",
                                            "1.0"))
    retries = max(1, retries)
    last: Exception | None = None
    for attempt in range(1, retries + 1):
        try:
            jax.distributed.initialize()
            return
        except (RuntimeError, ValueError) as e:
            msg = str(e).lower()
            # double-init is fine (the harness beat us to it).  jax 0.4.37
            # phrases it "distributed.initialize should only be called
            # once."; older/newer versions say "already initialized".
            # Match those SPECIFIC phrasings — a bare "already" would also
            # swallow grpc's "Address already in use" (a stale coordinator
            # port), which is a real failure that must retry/raise.
            # And only on the FIRST attempt: jax assigns its global client
            # BEFORE connect(), so after a failed attempt the retry raises
            # this same message about the half-initialized carcass —
            # honoring it then would silently report success on a runtime
            # that never connected
            if ("already initialized" in msg
                    or "only be called once" in msg):
                if attempt == 1:
                    print(f"tmlauncher: distributed init skipped: {e}",
                          file=sys.stderr)
                    return
            else:
                last = e
                print(f"tmlauncher: distributed init attempt "
                      f"{attempt}/{retries} failed: {e}", file=sys.stderr)
            try:
                # clear the half-initialized global state so the retry is
                # a real fresh initialize, not a double-init error
                jax.distributed.shutdown()
            except Exception:  # lint: swallow-ok — nothing to shut down
                pass
            if attempt < retries:
                sleep(backoff_base * (2 ** (attempt - 1)))
    raise DistributedInitError(
        f"could not join the multi-controller runtime after {retries} "
        f"attempts (pod env vars present, so a single-host fallback would "
        f"desynchronize the slice): {last}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmlauncher",
        description="Launch a theanompi_tpu training session on the local "
        "mesh (run on every host of a pod for multi-host).",
        # no prefix abbreviation: the supervisor strips its own flags from
        # the child argv by exact spelling — an abbreviated '--superv'
        # sneaking through would make the child a supervisor too
        # (recursive spawning)
        allow_abbrev=False,
    )
    p.add_argument("--rule", default="BSP",
                   choices=["BSP", "EASGD", "GOSGD", "LocalSGD"])
    p.add_argument("--devices", default="all",
                   help="worker count or 'all' (default)")
    p.add_argument("--modelfile", default="theanompi_tpu.models.wide_resnet")
    p.add_argument("--modelclass", default="WideResNet")
    p.add_argument("--set", dest="model_set", action="append", default=[],
                   metavar="K=V", help="model config entry (repeatable)")
    p.add_argument("--rule-set", dest="rule_set", action="append", default=[],
                   metavar="K=V", help="rule config entry (repeatable)")
    p.add_argument("--config-json", default=None,
                   help="path to a JSON file with {'model': {...}, 'rule': {...}}")
    p.add_argument("--record-dir", default=None)
    p.add_argument("--telemetry-dir", default=None,
                   help="enable structured telemetry: per-rank JSONL event "
                   "sinks under this dir; rank 0 writes trace.json "
                   "(Perfetto-loadable) + summary.json (cross-rank skew) "
                   "at the end of the run.  Also enables live health "
                   "(HEALTH.json verdicts — watch with tmhealth) and the "
                   "crash flight recorder (blackbox.json); tune/disable "
                   "via --rule-set telemetry_health=... / "
                   "telemetry_blackbox=N (ISSUE 13).  Step-time "
                   "attribution (attr.* gauges + ATTRIB.json — inspect "
                   "with tmprof) rides the same opt-in; disable via "
                   "--rule-set telemetry_profile=False, and open a "
                   "bounded jax.profiler device-trace window with "
                   "--rule-set profile_dir=DIR profile_window=START:STOP "
                   "(ISSUE 16).  Under --supervise "
                   "a critical hang verdict kills and restarts the child "
                   "without waiting out --hang-timeout")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compilation-cache directory, shared "
                   "across runs: a restart/resume/sweep subprocess with the "
                   "same programs loads compiled executables instead of "
                   "repaying the full compile (also: THEANOMPI_COMPILE_CACHE "
                   "env var)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--resume-force", action="store_true",
                   help="override the checkpoint run-fingerprint check: "
                   "resume even though the mesh / exchange strategy / "
                   "model config differ from the checkpoint's (ISSUE 5; "
                   "normally a hard refusal)")
    p.add_argument("--resume-reshard", action="store_true",
                   help="elastic resume (ISSUE 8; implies --resume): a "
                   "checkpoint written under a different data-parallel "
                   "topology is re-laid-out onto the live mesh — params "
                   "re-replicated, zero1 optimizer shards re-padded and "
                   "re-scattered, LR rescaled by the linear-scaling rule "
                   "(stderr-warned).  Model-identity mismatches still "
                   "refuse; unplannable transitions (tp/pp meshes) exit 79")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quiet", action="store_true")
    sup = p.add_argument_group(
        "supervision (ISSUE 4: auto-restart + resume)")
    sup.add_argument("--supervise", action="store_true",
                     help="run the session in a supervised child process: "
                     "classify exits (crash/preemption/hang/config), "
                     "restart with bounded exponential backoff and "
                     "--resume, and write a resilience.json audit trail")
    sup.add_argument("--max-restarts", type=int, default=3,
                     help="crash/hang restart budget (preemption exits are "
                     "budget-free); default 3")
    sup.add_argument("--backoff-base", type=float, default=1.0,
                     help="first restart delay in seconds, doubling per "
                     "restart (jittered, capped); default 1.0")
    sup.add_argument("--hang-timeout", type=float, default=None,
                     help="supervisor-side heartbeat-staleness kill switch "
                     "in seconds (backstop for a child too wedged to run "
                     "its own watchdog; off by default)")
    sup.add_argument("--elastic", action="store_true",
                     help="elastic supervision (ISSUE 8; implies "
                     "--supervise): re-probe the available device count "
                     "before every restart, rewrite the child's --devices "
                     "to it, and resume with --resume-reshard — the pod "
                     "comes back with fewer chips and keeps training "
                     "(THEANOMPI_ELASTIC_DEVICES overrides the probe)")
    p.add_argument("--sentinel", default=None,
                   choices=["abort", "skip_batch", "rollback"],
                   help="non-finite loss/grad guard policy (shorthand for "
                   "--rule-set sentinel_policy=...); off when absent")
    return p


#: supervision-layer flags stripped from the child's command line
#: (value = how many operands follow the flag)
_SUPERVISOR_FLAGS = {"--supervise": 0, "--max-restarts": 1,
                     "--backoff-base": 1, "--hang-timeout": 1,
                     "--elastic": 0}


def _strip_supervision_args(argv: list[str]) -> list[str]:
    out, i = [], 0
    while i < len(argv):
        key = argv[i].split("=", 1)[0]
        if key in _SUPERVISOR_FLAGS:
            i += 1
            if "=" not in argv[i - 1]:
                i += _SUPERVISOR_FLAGS[key]
            continue
        out.append(argv[i])
        i += 1
    return out


def _supervisor_heartbeat_path(args, base: str) -> str:
    """The supervisor must watch the SAME file the child writes: a
    ``heartbeat_path`` rule key overrides the ``THEANOMPI_HEARTBEAT`` env
    in the child, so honor it here too — a mismatch would make
    ``--hang-timeout`` kill every healthy child as silent."""
    try:
        _, rule_config = _build_configs(args)
    except Exception:  # lint: swallow-ok — the child will report it
        rule_config = {}
    return (rule_config.get("heartbeat_path")
            or os.path.join(base, "heartbeat.json"))


def _supervise(argv: list[str], args) -> int:
    """The --supervise path: this process becomes the Supervisor; the
    actual session runs in child launcher processes (a fresh process is
    the only thing a SIGKILL/OOM/wedged-runtime can't take down with it,
    and the only way to re-init a jax backend cleanly)."""
    from theanompi_tpu.resilience import EXIT_CONFIG, run_job, supervised

    if supervised():
        # belt-and-braces recursion guard: a supervised child must never
        # itself supervise (argv stripping should prevent this; if it ever
        # leaks through, fail loudly instead of forking forever)
        print("tmlauncher: error: config: --supervise inside a supervised "
              "child (recursive supervision)", file=sys.stderr, flush=True)
        return EXIT_CONFIG

    base = args.checkpoint_dir or "."
    os.makedirs(base, exist_ok=True)
    if not args.checkpoint_dir:
        print("tmlauncher: warning: --supervise without --checkpoint-dir — "
              "restarts will redo all work (nothing to resume from)",
              file=sys.stderr)
    heartbeat = _supervisor_heartbeat_path(args, base)
    child = ([sys.executable, "-m", "theanompi_tpu.launcher"]
             + _strip_supervision_args(argv))
    # the per-attempt run/classify/backoff core is the shared run_job
    # seam — the fleet scheduler drives the same loop for its children
    return run_job(
        child,
        max_restarts=args.max_restarts,
        backoff_base=args.backoff_base,
        hang_timeout_s=args.hang_timeout,
        heartbeat_path=heartbeat,
        resilience_path=os.path.join(base, "resilience.json"),
        telemetry_dir=args.telemetry_dir,
        seed=args.seed,
        # ISSUE 8: elastic restarts re-probe the device inventory and
        # resume with the reshard gate open
        elastic=args.elastic,
        resume_args=(("--resume", "--resume-reshard") if args.elastic
                     else ("--resume",)),
    ).exit_code


def _compile_cache_usable(args) -> bool:
    """Work around a jaxlib 0.4.3x CPU-backend bug found while building the
    supervisor (ISSUE 4): loading persistent-compilation-cache executables
    into a *resumed* session intermittently corrupts the native heap
    (malloc "invalid next size" / SIGSEGV under load — reproduced only
    with the resume + warm-cache combination; fresh runs reading the
    cache and resumed runs writing a cold cache are both fine).  Until
    the toolchain moves, a resumed CPU-backend session skips the cache
    and repays the compile; TPU backends (a different executable
    serialization path) keep it.  ``THEANOMPI_RESUME_COMPILE_CACHE=1``
    forces the cache back on, ``=0`` forces it off everywhere.
    """
    if not args.resume:
        return True
    force = os.environ.get("THEANOMPI_RESUME_COMPILE_CACHE")
    if force is not None:
        return force.strip().lower() not in ("0", "false", "no", "off", "")
    import jax

    if jax.default_backend() != "cpu":
        return True
    print("tmlauncher: compile cache disabled for this resumed CPU-backend "
          "session (jaxlib 0.4.3x cache-load instability; "
          "THEANOMPI_RESUME_COMPILE_CACHE=1 forces it on)", file=sys.stderr)
    return False


def _error_line(phase: str, e: BaseException) -> None:
    """The one-line exit-code-contract error report (ISSUE 4 satellite):
    no raw traceback unless THEANOMPI_DEBUG asks for one."""
    print(f"tmlauncher: error: {phase}: {type(e).__name__}: {e}",
          file=sys.stderr, flush=True)
    if os.environ.get("THEANOMPI_DEBUG"):
        import traceback

        traceback.print_exc()


#: setup-phase exception types that will not fix themselves on restart
_CONFIG_ERRORS = (ImportError, AttributeError, TypeError, ValueError,
                  KeyError, IndexError, FileNotFoundError,
                  IsADirectoryError, NotADirectoryError,
                  json.JSONDecodeError)


def _build_configs(args) -> tuple[dict, dict]:
    model_config: dict = {}
    rule_config: dict = {}
    if args.config_json:
        with open(args.config_json) as f:
            blob = json.load(f)
        model_config.update(blob.get("model", {}))
        rule_config.update(blob.get("rule", {}))
    model_config.update(_parse_kv(args.model_set))
    rule_config.update(_parse_kv(args.rule_set))
    rule_config.setdefault("seed", args.seed)
    if args.record_dir:
        rule_config["record_dir"] = args.record_dir
    if args.telemetry_dir:
        rule_config["telemetry_dir"] = args.telemetry_dir
    if args.checkpoint_dir:
        rule_config["checkpoint_dir"] = args.checkpoint_dir
    if args.sentinel:
        rule_config.setdefault("sentinel_policy", args.sentinel)
    if args.resume:
        rule_config["resume"] = True
    if args.resume_reshard:
        # ISSUE 8: the elastic flag IS a resume (nothing to reshard onto
        # a fresh run), with the fingerprint gate opened for replanning
        rule_config["resume"] = True
        rule_config["resume_reshard"] = True
    if args.resume_force:
        rule_config["resume_force"] = True
    if args.quiet:
        rule_config["verbose"] = False
    return model_config, rule_config


def main(argv: list[str] | None = None) -> int:
    """Exit-code contract (ISSUE 4/5/8; see the README table): 0 clean,
    70 training crash, 75 resumable preemption exit, 76 watchdog hang,
    77 checkpoint recovery chain exhausted, 78 config error, 79 elastic
    reshard refused (unplannable topology transition) — each reported as
    ONE ``tmlauncher: ...`` stderr line
    (set THEANOMPI_DEBUG=1 for the full traceback), so the supervisor —
    and any outer scheduler — can classify without parsing tracebacks."""
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = build_parser().parse_args(argv)
    if args.elastic:
        args.supervise = True  # elastic IS supervision with re-probing
    if args.supervise:
        return _supervise(argv, args)

    from theanompi_tpu.resilience import (
        EXIT_CKPT,
        EXIT_CONFIG,
        EXIT_CRASH,
        EXIT_PREEMPTED,
        EXIT_RESHARD,
        PreemptionExit,
    )
    from theanompi_tpu.utils.checkpoint import (
        CheckpointCorruptError,
        CheckpointFingerprintError,
        CheckpointReshardError,
    )

    # -- config phase: wrong flags/files will not fix themselves ------------
    try:
        model_config, rule_config = _build_configs(args)
        import theanompi_tpu

        rule_cls = getattr(theanompi_tpu, args.rule)
        devices = None if args.devices == "all" else int(args.devices)
    except SystemExit as e:  # _parse_kv-style one-line config rejections
        print(f"tmlauncher: error: config: {e}", file=sys.stderr, flush=True)
        return EXIT_CONFIG
    except Exception as e:
        _error_line("config", e)
        return EXIT_CONFIG

    # -- environment phase: transient by nature, restartable ----------------
    try:
        _maybe_init_distributed()
        if args.compile_cache_dir and _compile_cache_usable(args):
            # before the first jit dispatch (rule.init compiles lazily)
            from theanompi_tpu.parallel.mesh import setup_compile_cache

            setup_compile_cache(args.compile_cache_dir)
    except Exception as e:
        _error_line("distributed init", e)
        return EXIT_CRASH

    # -- init phase: model import / mesh build / compile / resume ----------
    try:
        rule = rule_cls(config=rule_config)
        rule.init(
            devices=devices,
            modelfile=args.modelfile,
            modelclass=args.modelclass,
            model_config=model_config,
        )
    except CheckpointReshardError as e:
        # ISSUE 8: --resume-reshard was set but the transition cannot be
        # planned (tp/pp mesh, layout-family change, bucket mismatch) —
        # a DISTINCT code: the elastic supervisor must stop, not loop
        _error_line("reshard", e)
        return EXIT_RESHARD
    except CheckpointFingerprintError as e:
        # a topology change, not corruption: restarting won't fix it, and
        # the user holds the override (--resume-force, or --resume-reshard
        # when the mismatch is reshardable) — config class
        _error_line("resume", e)
        return EXIT_CONFIG
    except CheckpointCorruptError as e:
        # ISSUE 5: the recovery chain is exhausted — every retained
        # checkpoint failed verification (the bad files are under
        # <checkpoint-dir>/corrupt/).  Distinct code: the supervisor must
        # NOT restart into the same empty chain
        _error_line("checkpoint", e)
        return EXIT_CKPT
    except _CONFIG_ERRORS as e:
        _error_line("init", e)
        return EXIT_CONFIG
    except Exception as e:
        _error_line("init", e)
        return EXIT_CRASH

    # -- training phase -----------------------------------------------------
    try:
        recorder = rule.wait()
    except PreemptionExit as e:
        print(f"tmlauncher: preempted: {e} (exit {EXIT_PREEMPTED}; rerun "
              f"with --resume or under --supervise)", file=sys.stderr,
              flush=True)
        return EXIT_PREEMPTED
    except KeyboardInterrupt:
        raise  # a human's ^C is not a crash to classify
    except CheckpointReshardError as e:
        _error_line("reshard", e)
        return EXIT_RESHARD
    except CheckpointCorruptError as e:
        # a sentinel rollback can exhaust the chain mid-training too
        _error_line("checkpoint", e)
        return EXIT_CKPT
    except Exception as e:
        _error_line("training", e)
        return EXIT_CRASH
    if not args.quiet:
        last = {k: v[-1] for k, v in recorder.val_history.items() if v}
        print(f"tmlauncher: done. final val: {last}", flush=True)
        if args.telemetry_dir:
            print(f"tmlauncher: telemetry in {args.telemetry_dir} "
                  f"(trace.json for Perfetto, summary.json for skew)",
                  flush=True)
    return 0


if __name__ == "__main__":
    # Subprocess entries only (tier-1 velocity, ISSUE 17 satellite): a
    # test session exports THEANOMPI_COMPILE_CACHE at one shared tmpdir,
    # and every ``python -m theanompi_tpu.launcher`` child that doesn't
    # pass --compile-cache-dir picks it up here — one warm XLA cache
    # across all subprocess e2e tests.  Deliberately NOT in main():
    # in-process launcher.main([...]) calls keep their explicit-flag-only
    # behavior, and the env supplies a default through the normal args
    # path, so the resumed-CPU cache-load guard (_compile_cache_usable)
    # still gates it.
    _argv = sys.argv[1:]
    _cache = os.environ.get("THEANOMPI_COMPILE_CACHE")
    if _cache and not any(a.startswith("--compile-cache-dir")
                          for a in _argv):
        _argv += ["--compile-cache-dir", _cache]
    raise SystemExit(main(_argv))
