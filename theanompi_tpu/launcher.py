"""tmlauncher: the CLI session launcher.

Reference (unverified — SURVEY.md §1/§3.1): ``tmlauncher``/``launch_session.py``
composed an ``mpirun`` command line placing one worker process per requested
``cudaN`` device (plus the EASGD server rank) and joined it.

TPU-native re-expression: there is no process tree to compose — the "cluster"
is the device mesh.  The launcher parses the same launch intent
(rule, device count, modelfile/modelclass, config) and drives
``Rule.init(...).wait()`` in-process.  On a multi-host pod, run this same
command on every host under the JAX multi-controller runtime
(``jax.distributed.initialize`` is called automatically when the standard TPU
pod environment variables are present); each host sees the global mesh.

Examples::

    tmlauncher --rule BSP --devices 8 \
        --modelfile theanompi_tpu.models.resnet50 --modelclass ResNet50 \
        --set batch_size=64 --set n_epochs=90 \
        --rule-set exch_strategy=psum_bf16 --record-dir ./record

    tmlauncher --rule EASGD --devices all --rule-set tau=8 \
        --checkpoint-dir ./ckpt --resume
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys


def _parse_kv(pairs: list[str]) -> dict:
    """k=v pairs with Python-literal values (`lr=0.1`, `lrn=False`,
    `stage_blocks=(3,4,6,3)`); bare strings stay strings."""
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"expected key=value, got {pair!r}")
        k, v = pair.split("=", 1)
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def _maybe_init_distributed() -> None:
    """Join the JAX multi-controller runtime on a pod (no-op on one host)."""
    if os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    ):
        import jax

        try:
            jax.distributed.initialize()
        except (RuntimeError, ValueError) as e:  # already initialized / local
            print(f"tmlauncher: distributed init skipped: {e}", file=sys.stderr)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmlauncher",
        description="Launch a theanompi_tpu training session on the local "
        "mesh (run on every host of a pod for multi-host).",
    )
    p.add_argument("--rule", default="BSP",
                   choices=["BSP", "EASGD", "GOSGD", "LocalSGD"])
    p.add_argument("--devices", default="all",
                   help="worker count or 'all' (default)")
    p.add_argument("--modelfile", default="theanompi_tpu.models.wide_resnet")
    p.add_argument("--modelclass", default="WideResNet")
    p.add_argument("--set", dest="model_set", action="append", default=[],
                   metavar="K=V", help="model config entry (repeatable)")
    p.add_argument("--rule-set", dest="rule_set", action="append", default=[],
                   metavar="K=V", help="rule config entry (repeatable)")
    p.add_argument("--config-json", default=None,
                   help="path to a JSON file with {'model': {...}, 'rule': {...}}")
    p.add_argument("--record-dir", default=None)
    p.add_argument("--telemetry-dir", default=None,
                   help="enable structured telemetry: per-rank JSONL event "
                   "sinks under this dir; rank 0 writes trace.json "
                   "(Perfetto-loadable) + summary.json (cross-rank skew) "
                   "at the end of the run")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compilation-cache directory, shared "
                   "across runs: a restart/resume/sweep subprocess with the "
                   "same programs loads compiled executables instead of "
                   "repaying the full compile (also: THEANOMPI_COMPILE_CACHE "
                   "env var)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quiet", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    _maybe_init_distributed()
    if args.compile_cache_dir:
        # before the first jit dispatch (rule.init compiles lazily later)
        from theanompi_tpu.parallel.mesh import setup_compile_cache

        setup_compile_cache(args.compile_cache_dir)

    model_config: dict = {}
    rule_config: dict = {}
    if args.config_json:
        with open(args.config_json) as f:
            blob = json.load(f)
        model_config.update(blob.get("model", {}))
        rule_config.update(blob.get("rule", {}))
    model_config.update(_parse_kv(args.model_set))
    rule_config.update(_parse_kv(args.rule_set))
    rule_config.setdefault("seed", args.seed)
    if args.record_dir:
        rule_config["record_dir"] = args.record_dir
    if args.telemetry_dir:
        rule_config["telemetry_dir"] = args.telemetry_dir
    if args.checkpoint_dir:
        rule_config["checkpoint_dir"] = args.checkpoint_dir
    if args.resume:
        rule_config["resume"] = True
    if args.quiet:
        rule_config["verbose"] = False

    import theanompi_tpu

    rule_cls = getattr(theanompi_tpu, args.rule)
    devices = None if args.devices == "all" else int(args.devices)

    rule = rule_cls(config=rule_config)
    rule.init(
        devices=devices,
        modelfile=args.modelfile,
        modelclass=args.modelclass,
        model_config=model_config,
    )
    recorder = rule.wait()
    if not args.quiet:
        last = {k: v[-1] for k, v in recorder.val_history.items() if v}
        print(f"tmlauncher: done. final val: {last}", flush=True)
        if args.telemetry_dir:
            print(f"tmlauncher: telemetry in {args.telemetry_dir} "
                  f"(trace.json for Perfetto, summary.json for skew)",
                  flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
