"""The exit-code contract, in one place (sysexits.h-adjacent).

Both halves of the resilience layer need these — the sentinel raises
``PreemptionExit(EXIT_PREEMPTED)`` inside the training process, the
supervisor classifies child exit codes outside it — and a drifted
duplicate would silently turn preemptions into budget-burning crashes,
so the constants live in this leaf module with no other imports.
"""

EXIT_CLEAN = 0
EXIT_CRASH = 70      # EX_SOFTWARE: unhandled training exception
EXIT_PREEMPTED = 75  # EX_TEMPFAIL: clean resumable preemption exit
EXIT_HANG = 76       # EX_PROTOCOL (repurposed): watchdog-confirmed stall
EXIT_CKPT = 77       # EX_NOPERM (repurposed): checkpoint recovery chain
#                      exhausted — no verifiable checkpoint to resume from
#                      (fatal: a restart would walk the same empty chain)
EXIT_CONFIG = 78     # EX_CONFIG: bad flags/config/model import
EXIT_RESHARD = 79    # just past sysexits: elastic resume could not replan
#                      the checkpoint onto the live topology (tp/pp mesh,
#                      zero1<->per-leaf layout change, bucket mismatch) —
#                      fatal: re-resharding the same pair cannot succeed
