"""Supervised training: auto-restart with backoff + resume (ISSUE 4).

The t5x/orbax-style auto-resume loop, built into the framework instead of
bolted onto each driver: the supervisor runs the training session (the
launcher's ``rule.init(...).wait()``) in a **child process**, classifies
how it died, and restarts it — auto-resuming from the latest checkpoint —
under a bounded exponential-backoff budget.  A child process, not a
thread or a try/except: SIGKILL, OOM, a wedged XLA runtime and a
preempting hypervisor all kill *processes*, and only a fresh process can
re-initialize a jax backend cleanly (the same lesson ``bench.py``'s
re-exec retry learned in round 4).

Exit-code contract (see the package ``__init__`` / README table)::

    0              clean        -> done
    75 / -SIGTERM  preemption   -> restart; does NOT count against budget
    76             hang         -> restart (counts)
    77             checkpoint   -> fatal (the recovery chain is exhausted)
    2 / 78         config       -> fatal, no restart (it won't fix itself)
    79             reshard      -> fatal (the transition is unplannable)
    anything else  crash        -> restart (counts)

Every attempt is recorded — cause, exit code, duration, time lost — to a
crash-safe ``resilience.json`` summary, and mirrored as JSONL events into
the telemetry directory (``supervisor.jsonl``; a separate file because
each child attempt truncates and rewrites the per-rank event sinks).

Hang detection is layered: the child's in-process :class:`~theanompi_tpu.
resilience.watchdog.Watchdog` (median-adaptive, exits ``EXIT_HANG``
itself) is primary; the supervisor's ``hang_timeout_s`` is the blunt
mtime-based backstop for a child too wedged to run even its watchdog
thread, enabled only when configured (``--hang-timeout``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

from theanompi_tpu.resilience.codes import (
    EXIT_CKPT,
    EXIT_CLEAN,
    EXIT_CONFIG,
    EXIT_CRASH,
    EXIT_HANG,
    EXIT_PREEMPTED,
    EXIT_RESHARD,
)
from theanompi_tpu.resilience.events import read_events
from theanompi_tpu.resilience.watchdog import heartbeat_age_s

#: restart-budget-exempt preemptions still need SOME bound, or a
#: preempt-loop (bad zone) supervises forever
MAX_PREEMPTIONS = 64


def classify_exit(returncode: int) -> str:
    """-> 'clean' | 'preemption' | 'hang' | 'config' | 'checkpoint' |
    'reshard' | 'crash'."""
    if returncode == EXIT_CLEAN:
        return "clean"
    # -SIGTERM: the preemptor's signal landed before (or instead of) the
    # child's cooperative handler — still a preemption, but the child had
    # no chance to checkpoint, so resume falls back to the last epoch
    if returncode in (EXIT_PREEMPTED, -signal.SIGTERM):
        return "preemption"
    if returncode == EXIT_HANG:
        return "hang"
    # ISSUE 5: the child's checkpoint recovery chain is exhausted — every
    # retained checkpoint failed verification and was quarantined.  A
    # restart would walk the same (now empty) chain: fatal, like config
    if returncode == EXIT_CKPT:
        return "checkpoint"
    # ISSUE 8: the elastic reshard was refused (tp/pp mesh, layout-family
    # change, bucket mismatch).  Replanning the same transition cannot
    # succeed: fatal, never a restart loop
    if returncode == EXIT_RESHARD:
        return "reshard"
    # 2 is argparse's usage-error exit
    if returncode in (EXIT_CONFIG, 2):
        return "config"
    return "crash"


def probe_device_count(env: dict | None = None, *,
                       timeout_s: float = 120.0, log=None) -> int | None:
    """The live accelerator inventory, or ``None`` when unknowable.

    Shared by the elastic :class:`Supervisor` (per-restart re-probe) and
    the fleet ledger (device-pool discovery): the
    ``THEANOMPI_ELASTIC_DEVICES`` env override first (operators who
    already know the slice size), else a fresh ``python -c "import jax;
    ..."`` subprocess — a SUBPROCESS because only an uninitialized
    backend sees the current inventory (and this stdlib-only module must
    not import jax).  A cpu-backend answer without an explicit
    ``JAX_PLATFORMS`` cpu pin is a FAILED probe, not a 1-chip topology.
    """
    def _log(msg: str) -> None:
        if log is not None:
            log(msg)

    def _valid(n: int, source: str) -> int | None:
        if n < 1:
            _log(f"ignoring nonsensical device count {n} from {source}")
            return None
        return n

    override = os.environ.get("THEANOMPI_ELASTIC_DEVICES")
    if override:
        try:
            return _valid(int(override), "THEANOMPI_ELASTIC_DEVICES")
        except ValueError:
            _log(f"ignoring non-integer "
                 f"THEANOMPI_ELASTIC_DEVICES={override!r}")
    env = dict(os.environ) if env is None else env
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()), "
             "jax.default_backend())"],
            env=env, capture_output=True, text=True, timeout=timeout_s)
        if out.returncode != 0:
            _log(f"device probe exited {out.returncode}: "
                 f"{out.stderr.strip()[-200:]}")
            return None
        count_s, backend = out.stdout.strip().splitlines()[-1].split()
        if backend == "cpu" and "cpu" not in env.get(
                "JAX_PLATFORMS", "").lower():
            # jax silently falls back to the CPU backend when an
            # accelerator plugin fails to init: on a TPU VM that is a
            # FAILED probe ("1 cpu device"), not a 1-chip topology —
            # resharding onto it would keep "training" on host CPU
            _log(f"device probe fell back to the cpu backend "
                 f"({count_s} device(s)) but JAX_PLATFORMS does not pin "
                 f"cpu; treating as a failed probe")
            return None
        return _valid(int(count_s), "jax probe")
    except (OSError, subprocess.SubprocessError, ValueError,
            IndexError) as e:
        _log(f"device probe failed: {e}")
        return None


class Supervisor:
    """Run a child command under restart supervision.

    ``child_cmd`` is the full argv of one training attempt;
    ``resume_args`` (default ``("--resume",)``) are appended from the
    second attempt on, so restarts pick up the latest checkpoint while the
    first attempt honors exactly what the user asked for.

    ``elastic=True`` (ISSUE 8): before every RESTART the supervisor
    re-probes the live device count and rewrites the child's ``--devices``
    operand to what is actually there — "the pod comes back with fewer
    chips and keeps training".  Pair it with
    ``resume_args=("--resume", "--resume-reshard")`` (the launcher's
    ``--elastic`` flag does) so the child replans the checkpoint onto the
    probed topology instead of refusing the fingerprint mismatch.  The
    probe: ``device_probe()`` when injected (tests), else the
    ``THEANOMPI_ELASTIC_DEVICES`` env override (operators who already know
    the new slice size), else a fresh ``python -c "import jax; ..."``
    subprocess — a SUBPROCESS because only an uninitialized backend sees
    the current device inventory (and this stdlib-only module must not
    import jax).  Per-attempt device counts and reshard outcomes land in
    the ``resilience.json`` attempt records.
    """

    def __init__(self, child_cmd: list[str], *, max_restarts: int = 3,
                 backoff_base: float = 1.0, backoff_cap: float = 60.0,
                 jitter: float = 0.5, hang_timeout_s: float | None = None,
                 poll_s: float = 0.2, heartbeat_path: str | None = None,
                 resilience_path: str = "resilience.json",
                 telemetry_dir: str | None = None,
                 resume_args: tuple[str, ...] = ("--resume",),
                 env: dict | None = None, seed: int = 0,
                 sleep=None, elastic: bool = False, device_probe=None,
                 probe_timeout_s: float = 120.0):
        self.child_cmd = list(child_cmd)
        self.max_restarts = max_restarts
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.jitter = jitter
        if hang_timeout_s is not None and hang_timeout_s < 3.0:
            # the child's heartbeat writer rate-limits to ~1/s: a timeout
            # at or below that kills every healthy child as "hung"
            self._log(f"hang_timeout_s={hang_timeout_s:g} is below the "
                      f"heartbeat write interval; clamping to 3.0s")
            hang_timeout_s = 3.0
        self.hang_timeout_s = hang_timeout_s
        self.poll_s = poll_s
        self.heartbeat_path = heartbeat_path
        self.resilience_path = resilience_path
        self.telemetry_dir = telemetry_dir
        self.resume_args = tuple(resume_args)
        self.env = dict(env or {})
        self.sleep = sleep
        self.elastic = elastic
        self.device_probe = device_probe
        self.probe_timeout_s = probe_timeout_s
        self._last_devices: int | None = None
        self._seen_reshard_applies = 0
        self._rng = random.Random(seed)  # jittered backoff, reproducible
        self.attempts: list[dict] = []
        self._proc: subprocess.Popen | None = None
        self._terminated = False
        # default backoff sleeper: an event wait, so a SIGTERM landing
        # DURING the backoff interrupts it instead of sleeping through the
        # preemption grace period (tests inject `sleep` to fake delays)
        self._term_event = threading.Event()

    # -- elastic topology probing (ISSUE 8) ----------------------------------
    def _valid_count(self, n: int, source: str) -> int | None:
        """A probed count must be a positive worker count — 0/negative is
        a failed probe (keep the previous topology), not a topology."""
        if n < 1:
            self._log(f"ignoring nonsensical device count {n} from "
                      f"{source}; keeping the previous topology")
            return None
        return n

    def _probe_devices(self, attempt: int) -> int | None:
        """The live device count, or None when unknowable (the attempt
        then runs with the previous topology unchanged)."""
        if self.device_probe is not None:
            try:
                return self._valid_count(int(self.device_probe()),
                                         "injected probe")
            # lint: swallow-ok — an injected probe may fail arbitrarily;
            # the failure is logged and the restart proceeds with the
            # previous topology instead of dying inside the supervisor
            except Exception as e:
                self._log(f"injected device probe failed: {e}")
                return None
        return probe_device_count(self._attempt_env(attempt),
                                  timeout_s=self.probe_timeout_s,
                                  log=self._log)

    @staticmethod
    def _with_devices(cmd: list[str], n: int) -> list[str]:
        """Rewrite the ``--devices`` operand (including ``--devices all``
        — "all" is exactly what changed) to the probed count.  A command
        without the flag is left alone: the child discovers all devices
        itself, which is already elastic."""
        out = list(cmd)
        for i, a in enumerate(out):
            if a == "--devices" and i + 1 < len(out):
                out[i + 1] = str(n)
                return out
            if a.startswith("--devices="):
                out[i] = f"--devices={n}"
                return out
        return out

    # -- one attempt ---------------------------------------------------------
    def _attempt_cmd(self, attempt: int) -> list[str]:
        cmd = list(self.child_cmd)
        if attempt > 1:
            cmd += [a for a in self.resume_args if a not in cmd]
            if self.elastic and self._last_devices is not None:
                cmd = self._with_devices(cmd, self._last_devices)
        return cmd

    def _attempt_env(self, attempt: int) -> dict:
        env = dict(os.environ)
        env.update(self.env)
        env["THEANOMPI_SUPERVISED"] = "1"
        env["THEANOMPI_ATTEMPT"] = str(attempt)
        if self.heartbeat_path:
            env["THEANOMPI_HEARTBEAT"] = self.heartbeat_path
        return env

    def _wait(self, proc: subprocess.Popen,
              started_s: float) -> tuple[int, bool]:
        """Poll the child; -> (returncode, killed_as_hung)."""
        # lint: wall-ok — compared against HEALTH.json file mtimes
        wall0 = time.time()
        next_health = 0.0
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc, False
            # real sleep, NOT the injected self.sleep: that seam fakes the
            # restart BACKOFF in tests; a faked poll would busy-spin here
            if self.hang_timeout_s is not None and self.heartbeat_path:
                age = heartbeat_age_s(self.heartbeat_path)
                if age is None:
                    # no heartbeat yet: measure from attempt start (compile
                    # time counts — size the timeout accordingly)
                    age = time.perf_counter() - started_s
                if age > self.hang_timeout_s:
                    self._log(f"no heartbeat for {age:.0f}s "
                              f"(> {self.hang_timeout_s:.0f}s); killing "
                              f"hung child pid {proc.pid}")
                    proc.kill()
                    return proc.wait(), True
            # ISSUE 13: the child's own health monitor publishing a
            # critical hang verdict beats waiting out hang_timeout_s —
            # preempt-and-restart NOW instead of trusting the blunt
            # mtime backstop (checked ~1/s, not every poll tick)
            if self.telemetry_dir:
                nowp = time.perf_counter()
                if nowp >= next_health:
                    next_health = nowp + 1.0
                    v = self._health_hung(wall0)
                    if v is not None:
                        self._log(
                            f"health: critical hang verdict "
                            f"({v.get('reason', 'no reason')}); killing "
                            f"hung child pid {proc.pid}")
                        proc.kill()
                        return proc.wait(), True
            time.sleep(self.poll_s)

    def _health_hung(self, wall0: float) -> dict | None:
        """A FRESH critical hang verdict from the child's ``HEALTH.json``
        (ISSUE 13), or None.  Freshness is the file mtime vs this
        attempt's wall start — a previous attempt's dying verdict must
        never kill a healthy restart.  Plain ``json``: this stdlib-only
        module does not import the telemetry package."""
        path = os.path.join(self.telemetry_dir, "HEALTH.json")
        try:
            if os.stat(path).st_mtime <= wall0:
                return None
            with open(path) as f:
                health = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(health, dict):
            return None
        for v in health.get("verdicts", []):
            if (isinstance(v, dict) and v.get("detector") == "hang"
                    and v.get("severity") == "critical"):
                return v
        return None

    def _fresh_json(self, filename: str, wall0: float) -> dict | None:
        """Parse ``<telemetry_dir>/<filename>`` when its mtime postdates
        this attempt's wall start; None otherwise."""
        if not self.telemetry_dir:
            return None
        path = os.path.join(self.telemetry_dir, filename)
        try:
            if os.stat(path).st_mtime < wall0:
                return None
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        return data if isinstance(data, dict) else None

    def _backoff_s(self, restarts: int) -> float:
        base = min(self.backoff_cap,
                   self.backoff_base * (2 ** max(0, restarts - 1)))
        return base * (1.0 + self.jitter * self._rng.random())

    def _forward_term(self, signum, frame) -> None:
        """A preempted VM TERMs the supervisor too: hand the signal to the
        child (whose preemption handler checkpoints and exits 75) and end
        supervision after it — never restart into a dying machine."""
        self._terminated = True
        self._term_event.set()  # wake a supervisor mid-backoff
        p = self._proc
        if p is not None and p.poll() is None:
            try:
                p.terminate()
            except OSError:  # lint: swallow-ok — child already gone
                pass

    def terminate(self) -> None:
        """Thread-safe preemption entry point (the fleet scheduler's):
        act exactly as a delivered SIGTERM — forward it to the child
        (whose cooperative handler checkpoints and exits 75), interrupt
        any backoff wait, and end supervision after the child's
        shutdown, never restarting."""
        self._forward_term(signal.SIGTERM, None)

    # -- the loop ------------------------------------------------------------
    def run(self) -> int:
        prev_term = None
        if threading.current_thread() is threading.main_thread():
            prev_term = signal.signal(signal.SIGTERM, self._forward_term)
        try:
            return self._run()
        finally:
            if prev_term is not None:
                signal.signal(signal.SIGTERM, prev_term)
            # abnormal exit (KeyboardInterrupt in the poll loop, a bug)
            # must not orphan a still-running training child — on every
            # normal path _proc is already None here
            p, self._proc = self._proc, None
            if p is not None and p.poll() is None:
                self._log(f"terminating child pid {p.pid} on abnormal "
                          f"supervisor exit")
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    def _run(self) -> int:
        t_run0 = time.perf_counter()
        attempt, restarts, preemptions = 0, 0, 0
        final = EXIT_CRASH
        if self.elastic:
            # baseline against events a PREVIOUS supervised run left in
            # the (carried-forward) resilience.json — only applies newer
            # than this run's start may stamp an attempt as resharded
            self._seen_reshard_applies = self._count_reshard_applies()
        while True:
            if self._terminated:
                # SIGTERM landed between attempts (during backoff): never
                # spawn a fresh child into a dying machine
                self._log("terminated during backoff; not restarting")
                final = EXIT_PREEMPTED
                break
            attempt += 1
            if self.heartbeat_path:
                try:
                    os.remove(self.heartbeat_path)  # stale mtime = insta-kill
                except OSError:
                    pass  # lint: swallow-ok — heartbeat already absent
            if self.elastic and attempt > 1:
                # re-probe what is actually there before every restart —
                # the previous death may BE a topology change (preempted
                # chips); the child gets the probed count + the reshard
                # flag (resume_args) and replans the checkpoint onto it
                probed = self._probe_devices(attempt)
                if probed is not None:
                    if probed != self._last_devices:
                        self._log(f"elastic: probed {probed} device(s) "
                                  f"for attempt {attempt}")
                    self._last_devices = probed
            cmd = self._attempt_cmd(attempt)
            self._log(f"attempt {attempt}: {' '.join(cmd)}")
            t0 = time.perf_counter()
            # lint: wall-ok — gates blackbox/HEALTH harvesting by mtime
            wall_t0 = time.time()
            proc = subprocess.Popen(cmd, env=self._attempt_env(attempt))
            self._proc = proc
            rc, hung = self._wait(proc, t0)
            # cleared only on the NORMAL path: an exception out of _wait
            # leaves _proc set so run()'s finally can terminate the child
            self._proc = None
            dur = time.perf_counter() - t0
            cause = "hang" if hung else classify_exit(rc)
            rec = {"attempt": attempt, "cause": cause, "exit_code": rc,
                   "duration_s": round(dur, 3)}
            if self.elastic and self._last_devices is not None:
                rec["devices"] = self._last_devices
            outcome = self._reshard_outcome(rc)
            if outcome is not None:
                rec["reshard"] = outcome
            if cause not in ("clean", "preemption"):
                # progress since the last published checkpoint is gone; the
                # attempt's whole duration is the honest upper bound
                rec["time_lost_s"] = round(dur, 3)
            # ISSUE 13: harvest the attempt's flight-recorder dump and
            # final health verdicts into the attempt record (mtime-gated:
            # a stale file from an earlier attempt is not THIS death).
            # The blackbox summary drops the event ring — resilience.json
            # is the index; the full ring stays in blackbox.json
            bb = self._fresh_json("blackbox.json", wall_t0)
            if bb is not None:
                rec["blackbox"] = {
                    k: bb[k] for k in ("reason", "error", "wall_time",
                                       "pid", "rank", "n_events",
                                       "fingerprint") if k in bb}
            hv = self._fresh_json("HEALTH.json", wall_t0)
            if hv is not None:
                bad = [v for v in hv.get("verdicts", [])
                       if isinstance(v, dict) and v.get("severity") != "ok"]
                if bad:
                    rec["health"] = bad
            self.attempts.append(rec)
            self._emit({"name": "supervisor.attempt", **rec})
            if cause == "clean":
                final = EXIT_CLEAN
                break
            if self._terminated:
                self._log("terminated; ending supervision after the "
                          "child's shutdown (no restart)")
                final = rc if rc > 0 else EXIT_PREEMPTED
                break
            if cause == "checkpoint":
                # no verifiable checkpoint left (the child already walked
                # the whole recovery chain and quarantined every rung):
                # restarting replays the same exhausted walk
                self._log(f"attempt {attempt} exhausted the checkpoint "
                          f"recovery chain (exit {rc}); not restarting — "
                          f"inspect <checkpoint-dir>/corrupt/ and "
                          f"resilience.json")
                final = rc
                break
            if cause == "reshard":
                # ISSUE 8: the transition is unplannable (tp/pp mesh,
                # layout-family change, bucket mismatch) — replanning the
                # same pair cannot succeed, so a restart is a fatal loop
                self._log(f"attempt {attempt} could not reshard the "
                          f"checkpoint onto the live topology (exit {rc}); "
                          f"not restarting — dry-run `python -m "
                          f"theanompi_tpu.utils.checkpoint --reshard-plan "
                          f"<checkpoint-dir> --to-devices N` to see why")
                final = rc
                break
            if cause == "config":
                if attempt == 1:
                    self._log(f"attempt 1 exited with a config error "
                              f"(exit {rc}); not restarting")
                    final = rc
                    break
                # a config classification appearing only on a RESTART is
                # suspect: attempt 1 got past init, so this is more likely
                # environmental fallout of the previous death (e.g. an
                # accelerator lock released lazily after a SIGKILL,
                # shrinking the visible device count) — burn budget and
                # retry rather than give up with restarts remaining
                self._log(f"attempt {attempt} exited with a config error "
                          f"(exit {rc}) AFTER a working first attempt; "
                          f"treating as a restartable crash")
                cause = "crash"
                self.attempts[-1]["cause"] = "crash(config-on-restart)"
            if cause == "preemption":
                preemptions += 1
                if preemptions > MAX_PREEMPTIONS:
                    self._log(f"{preemptions} preemptions; giving up")
                    final = rc if rc > 0 else EXIT_PREEMPTED
                    break
            else:
                restarts += 1
                if restarts > self.max_restarts:
                    self._log(f"restart budget exhausted "
                              f"({restarts - 1}/{self.max_restarts}); "
                              f"giving up after {cause} (exit {rc})")
                    final = rc if rc > 0 else EXIT_CRASH
                    break
            delay = self._backoff_s(max(1, restarts))
            budget = ("free" if cause == "preemption"
                      else f"{restarts}/{self.max_restarts}")
            self._log(f"attempt {attempt} ended: {cause} (exit {rc}); "
                      f"restart {budget} with resume in {delay:.1f}s")
            self._write_summary(final=None, t_run0=t_run0,
                                restarts=restarts, preemptions=preemptions)
            if self.sleep is not None:
                self.sleep(delay)
            else:
                self._term_event.wait(delay)  # interruptible by SIGTERM
        self._write_summary(final=final, t_run0=t_run0,
                            restarts=restarts, preemptions=preemptions)
        self._emit({"name": "supervisor.done", "final_exit": final,
                    "restarts": restarts, "preemptions": preemptions})
        return final

    def _count_reshard_applies(self) -> int:
        return sum(1 for e in read_events(self.resilience_path)
                   if e.get("name") == "reshard.apply")

    def _reshard_outcome(self, rc: int) -> str | None:
        """'applied' when the attempt recorded a fresh ``reshard.apply``
        event in resilience.json (the child's checkpointer writes them),
        'failed' when it died with the reshard exit code, None otherwise."""
        if not self.elastic:
            return None
        applies = self._count_reshard_applies()
        if applies > self._seen_reshard_applies:
            self._seen_reshard_applies = applies
            return "applied"
        if rc == EXIT_RESHARD:
            return "failed"
        return None

    # -- reporting -----------------------------------------------------------
    def summary(self, final, t_run0, restarts, preemptions) -> dict:
        return {
            "attempts": self.attempts,
            "restarts": restarts,
            "preemptions": preemptions,
            "time_lost_s": round(sum(a.get("time_lost_s", 0.0)
                                     for a in self.attempts), 3),
            "total_s": round(time.perf_counter() - t_run0, 3),
            "final_exit": final,  # None while still running
        }

    def _write_summary(self, **kw) -> None:
        """Crash-safe rewrite after every attempt, not just at the end —
        a supervisor killed mid-run still leaves the attempt record.
        ``events`` recorded into the same file by the child's checkpoint
        recovery chain (ISSUE 5: ``ckpt.fallback``/``ckpt.quarantine``)
        are carried forward, never clobbered by the rewrite."""
        path = self.resilience_path
        data = self.summary(**kw)
        events = read_events(path)
        if events:
            data["events"] = events
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            with open(path + ".tmp", "w") as f:
                json.dump(data, f, indent=1)
            os.replace(path + ".tmp", path)
        except OSError as e:
            self._log(f"could not write {path}: {e}")

    def _emit(self, event: dict) -> None:
        """Mirror supervisor events into the telemetry dir as JSONL.

        A dedicated ``supervisor.jsonl`` (append mode), NOT an
        ``events-rank*`` sink: each child attempt truncates those, and the
        aggregation pass must not mistake the supervisor for a rank."""
        if not self.telemetry_dir:
            return
        try:
            os.makedirs(self.telemetry_dir, exist_ok=True)
            line = json.dumps({"ts": time.time(),  # lint: wall-ok — log
                               "kind": "instant", **event})  # stamp
            # lint: atomic-publish-ok — JSONL audit stream; read_events
            # skips a torn final line, and losing the tail on crash is
            # exactly the crash being recorded
            with open(os.path.join(self.telemetry_dir,
                                   "supervisor.jsonl"), "a") as f:
                f.write(line + "\n")
        except OSError as e:
            self._log(f"could not write supervisor telemetry: {e}")

    @staticmethod
    def _log(msg: str) -> None:
        print(f"supervisor: {msg}", file=sys.stderr, flush=True)


@dataclasses.dataclass
class JobResult:
    """What one supervised job episode came to (``run_job``'s return)."""

    exit_code: int       #: the final exit code of the whole episode
    cause: str           #: the LAST attempt's classification
    attempts: list       #: per-attempt records (resilience.json shape)
    preempted: bool      #: episode ended by preemption (resumable later)

    @property
    def clean(self) -> bool:
        return self.exit_code == EXIT_CLEAN


def run_job(child_cmd: list[str], *, on_supervisor=None,
            **supervisor_kwargs) -> JobResult:
    """One supervised job episode: the per-attempt run/classify/backoff
    core behind both ``tmlauncher --supervise`` and the fleet scheduler.

    Builds a :class:`Supervisor` over ``child_cmd`` (all keyword options
    pass straight through) and runs it to a final exit code.
    ``on_supervisor``, when given, receives the Supervisor before the
    first attempt — the fleet scheduler registers the handle there so a
    priority preemption can :meth:`Supervisor.terminate` the episode
    from another thread.  ``run()`` installs its SIGTERM forwarder only
    on the main thread, so calling this from worker threads is safe.
    """
    sup = Supervisor(child_cmd, **supervisor_kwargs)
    if on_supervisor is not None:
        on_supervisor(sup)
    rc = sup.run()
    cause = (sup.attempts[-1]["cause"] if sup.attempts
             else classify_exit(rc))
    return JobResult(
        exit_code=rc, cause=cause, attempts=list(sup.attempts),
        preempted=sup._terminated or classify_exit(rc) == "preemption")
