"""Heartbeat + stall detection (ISSUE 4).

Two cooperating halves:

- **In-process** (:class:`Watchdog`): ``train_iter`` feeds :meth:`beat`
  after every step; a daemon thread compares time-since-last-beat against
  a configurable multiple of the *trailing median* step time (with an
  absolute floor, so a slow-but-steady model is never flagged).  On
  trigger it emits a ``watchdog.stall`` telemetry event and — under
  supervision — exits the process with :data:`~theanompi_tpu.resilience.
  EXIT_HANG` so the supervisor classifies the death as a hang and
  restarts from the latest checkpoint.  Adaptive by construction: no
  threshold to tune per model, and no trigger until at least three step
  durations exist (the first step's XLA compile never trips it).
- **Cross-process** (the heartbeat file): every beat also refreshes an
  atomic JSON heartbeat file (step counter + wall timestamp,
  rate-limited), which the supervisor watches by mtime as a backstop for
  the case the in-process watchdog cannot catch — a process wedged so
  hard (stuck in a C call holding the GIL, SIGSTOP'd, swapping) that even
  the watchdog thread stops running.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
from collections import deque

from theanompi_tpu.resilience.codes import EXIT_HANG
from theanompi_tpu.telemetry.metrics import RESILIENCE_INSTANTS

# registered event name (tmlint telemetry-registered-names): emissions
# from this package must come from the telemetry/metrics.py registry
WATCHDOG_STALL = RESILIENCE_INSTANTS[0]


class Heartbeat:
    """Atomic, rate-limited progress file: ``{"step": N, "time": wall}``."""

    def __init__(self, path: str, min_interval_s: float = 1.0):
        self.path = path
        self.min_interval_s = min_interval_s
        self._last_write = -float("inf")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def beat(self, step: int, force: bool = False) -> None:
        now = time.perf_counter()
        if not force and now - self._last_write < self.min_interval_s:
            return
        self._last_write = now
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                # wall time: the supervisor compares against ITS clock via
                # the file mtime, and the payload is for humans
                json.dump({"step": int(step), "pid": os.getpid(),
                           # lint: wall-ok — supervisor reads the file
                           # MTIME; this payload copy is for humans
                           "time": time.time()}, f)
            os.replace(tmp, self.path)  # a reader never sees a torn write
        except OSError:
            # a full disk must degrade the heartbeat, not kill training;
            # the supervisor's mtime backstop goes stale, which is the
            # honest signal for "this host can no longer prove liveness"
            pass  # lint: swallow-ok — full disk degrades the heartbeat;
            #       the stale-mtime backstop is the honest signal


def heartbeat_age_s(path: str) -> float | None:
    """Seconds since the heartbeat file last changed (supervisor side);
    None when the file does not exist yet."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    # lint: wall-ok — mtime is wall time; the age must use the same clock
    return max(0.0, time.time() - st.st_mtime)


class Watchdog:
    """Median-adaptive stall detector fed by ``train_iter``.

    ``escalate='exit'`` (the supervised default) hard-exits with
    ``exit_code`` on a confirmed stall; ``'warn'`` (the unsupervised
    default) prints one line and keeps going — an unsupervised user's run
    must never be killed by its own safety net.
    """

    def __init__(self, multiple: float = 10.0, min_timeout_s: float = 30.0,
                 poll_s: float = 1.0, window: int = 64,
                 heartbeat: Heartbeat | None = None, telemetry=None,
                 escalate: str = "warn", exit_code: int = EXIT_HANG,
                 _exit=os._exit, _clock=time.perf_counter):
        if escalate not in ("exit", "warn"):
            raise ValueError(f"escalate must be 'exit' or 'warn', "
                             f"got {escalate!r}")
        self.multiple = multiple
        self.min_timeout_s = min_timeout_s
        self.poll_s = poll_s
        self.heartbeat = heartbeat
        self.telemetry = telemetry
        self.escalate = escalate
        self.exit_code = exit_code
        self._exit = _exit
        self._clock = _clock
        self._durs: deque[float] = deque(maxlen=window)
        self._lock = threading.Lock()
        self._last_beat: float | None = None
        self._step = -1
        self._paused = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.triggered = False

    # -- producer side (training thread) -------------------------------------
    def beat(self, step: int) -> None:
        now = self._clock()
        with self._lock:
            if self._last_beat is not None:
                self._durs.append(now - self._last_beat)
            self._last_beat = now
            self._step = int(step)
        if self.heartbeat is not None:
            self.heartbeat.beat(step)

    def pause(self) -> None:
        """Suspend stall detection across legitimate beat-free stretches —
        epoch-boundary work (validation's first eval compile, the val
        sweep, checkpoint joins) takes arbitrarily long without a single
        train step, and must not read as a hang."""
        with self._lock:
            self._paused = True
        if self.heartbeat is not None:
            # proof of life for the supervisor's mtime backstop at the
            # boundary's entry (its --hang-timeout must still be sized
            # above the longest boundary — it is the blunt instrument)
            self.heartbeat.beat(self._step, force=True)

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            # the paused stretch must not count as no-progress time
            self._last_beat = self._clock()
        if self.heartbeat is not None:
            self.heartbeat.beat(self._step, force=True)

    # -- detector side -------------------------------------------------------
    def stall_threshold_s(self) -> float | None:
        """Current no-progress budget, or None while still calibrating
        (fewer than 3 observed step durations — the compile-heavy first
        steps must not define 'normal')."""
        with self._lock:
            if len(self._durs) < 3:
                return None
            median = statistics.median(self._durs)
        return max(self.multiple * median, self.min_timeout_s)

    def check(self, now: float | None = None) -> bool:
        """One detector pass; -> whether a stall was flagged (test seam —
        the daemon thread calls this every ``poll_s``)."""
        if self.triggered:
            return True
        threshold = self.stall_threshold_s()
        with self._lock:
            last, step, paused = self._last_beat, self._step, self._paused
        if paused or threshold is None or last is None:
            return False
        stalled_s = (self._clock() if now is None else now) - last
        if stalled_s <= threshold:
            return False
        self.triggered = True
        msg = (f"watchdog: no train-step progress for {stalled_s:.1f}s "
               f"(threshold {threshold:.1f}s = {self.multiple:g}x trailing "
               f"median) at step {step}")
        print(msg, file=sys.stderr, flush=True)
        if self.telemetry is not None:
            self.telemetry.instant(WATCHDOG_STALL, step=step,
                                   stalled_s=stalled_s,
                                   threshold_s=threshold,
                                   escalate=self.escalate)
        if self.escalate == "exit":
            flight = getattr(self.telemetry, "flight", None)
            if flight is not None:
                # last words before the hard exit (ISSUE 13): os._exit
                # runs no atexit/finally, so this dump is the ONLY
                # artifact a hang leaves beyond the exit code
                health = getattr(self.telemetry, "health", None)
                try:
                    flight.dump("hang",
                                health=(health.verdicts()
                                        if health is not None else None),
                                error=msg)
                except OSError:
                    pass  # lint: swallow-ok — the exit must proceed even
                    #       when the blackbox write fails (full disk)
            sys.stderr.flush()
            self._exit(self.exit_code)
        return True

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="resilience-watchdog",
                                            daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
