"""Supervised serving replicas (ISSUE 14): tmserve through run_job.

``tmserve --supervise`` must reach the PR 10 supervisor seam, but the
serving⊥training wall forbids ``serving/`` importing
``resilience.supervisor`` at any depth — so the supervision half lives
HERE, in the resilience layer (where the in-layer supervisor import is
legal), and ``serving/cli.py`` reaches it through one lazy import,
mirroring the launcher's ``_supervise`` seam.

Deliberately stdlib-only and serving-import-free (the resilience leaf
wall): the child is ``python -m theanompi_tpu.serving`` as a SUBPROCESS —
this module never touches engine/scheduler machinery.

Semantics differ from the training supervisor in one way: ``resume_args``
is EMPTY.  A restarted replica has nothing to "--resume" — it re-derives
its request stream from the seed and skips the ids its REQUESTS.jsonl
already recorded terminal (see :mod:`theanompi_tpu.serving.lifecycle`).
Graceful drain composes for free: the supervisor forwards SIGTERM to the
child, the child drains within ``--drain-s`` and exits 0, and the
``cause == "clean"`` check in the attempt loop classifies the episode
clean — no restart, no crash count.
"""

from __future__ import annotations

import os
import sys

from theanompi_tpu.resilience.supervisor import JobResult, run_job

#: tmserve flags the supervisor consumes (never forwarded to the child);
#: value = operand count, same stripping grammar as the launcher's
SERVE_SUPERVISOR_FLAGS = {"--supervise": 0, "--max-restarts": 1,
                          "--backoff-base": 1}


def strip_supervision_args(argv: list[str]) -> list[str]:
    out, i = [], 0
    while i < len(argv):
        key = argv[i].split("=", 1)[0]
        if key in SERVE_SUPERVISOR_FLAGS:
            i += 1
            if "=" not in argv[i - 1]:
                i += SERVE_SUPERVISOR_FLAGS[key]
            continue
        out.append(argv[i])
        i += 1
    return out


def serve_supervised(argv: list[str], *, max_restarts: int = 3,
                     backoff_base: float = 1.0,
                     telemetry_dir: str | None = None,
                     seed: int = 0) -> int:
    """Run ``tmserve`` as a supervised child replica; -> final exit code.

    The per-attempt resilience.json lands in the telemetry dir (or the
    cwd) — NEVER in ``--checkpoint-dir``, which serving only ever reads
    (a live trainer may own it; the read-only contract holds).
    """
    base = telemetry_dir or "."
    os.makedirs(base, exist_ok=True)
    child = ([sys.executable, "-m", "theanompi_tpu.serving"]
             + strip_supervision_args(argv))
    result: JobResult = run_job(
        child,
        max_restarts=max_restarts,
        backoff_base=backoff_base,
        resilience_path=os.path.join(base, "resilience.json"),
        telemetry_dir=telemetry_dir,
        seed=seed,
        resume_args=(),  # replicas re-derive state; tmserve has no --resume
    )
    return result.exit_code
