"""Non-finite guard + preemption handling (ISSUE 4).

**Sentinel.**  A NaN/Inf loss is the classic silent killer: by the time a
human notices, every parameter is NaN and the checkpoints have rotated.
The sentinel watches the train-loss stream (and, for ``skip_batch``, a
device-side gradient-norm guard compiled into the step) and applies one
of three policies:

- ``abort`` (the default when enabled): raise :class:`NonFiniteLossError`
  — under supervision that is a crash the supervisor restarts from the
  last checkpoint.
- ``skip_batch``: an on-device guard (see ``make_local_step``) computes
  ``ok = isfinite(loss) & isfinite(grad_norm²)`` — reduced across workers
  so replicas stay in lockstep — and selects the *old* params/state/opt
  state when the step was poisoned, so one bad batch costs one skipped
  update instead of the run.  The skip count is bounded
  (``sentinel_max_skips``); exhausting it raises.
- ``rollback``: reload the latest **verifiable** checkpoint in-process
  (bounded by ``sentinel_max_rollbacks``) and replay from there — for the
  transient blow-up an LR schedule or bad shard causes once.  Since
  ISSUE 5 the reload goes through the checkpoint recovery chain: a corrupt
  latest checkpoint is quarantined and the rollback lands on the newest
  verified ancestor instead of re-raising into a crash loop.

Detection honesty: the host-side check only *materializes* loss scalars
at the recorder's fenced print boundaries (per-step blocking would
serialize the dispatch pipeline — the same discipline the recorder and
telemetry spans follow), so abort/rollback trigger up to ``print_freq-1``
steps after the first bad loss.  The ``skip_batch`` device guard has zero
detection latency — the selection happens inside the compiled step.

**Preemption.**  :class:`PreemptGuard` turns SIGTERM (what a TPU-VM
maintenance event or spot reclaim sends) into a cooperative flag the run
loop checks between steps; the trainer then writes a final synchronous
checkpoint and raises :class:`PreemptionExit` — a ``SystemExit`` carrying
the distinct ``EXIT_PREEMPTED`` code the supervisor treats as
*resume-don't-count-against-the-restart-budget*.
"""

from __future__ import annotations

import signal
import sys
import threading

import numpy as np

from theanompi_tpu.resilience.codes import EXIT_PREEMPTED
from theanompi_tpu.telemetry.metrics import RESILIENCE_INSTANTS

# registered event names (tmlint telemetry-registered-names)
SENTINEL_SKIP, SENTINEL_NONFINITE = RESILIENCE_INSTANTS[1:3]

POLICIES = ("abort", "skip_batch", "rollback")


class NonFiniteLossError(RuntimeError):
    """Training produced a non-finite loss/grad-norm the policy could not
    absorb."""

    def __init__(self, message: str, step: int | None = None):
        super().__init__(message)
        self.step = step


class SentinelRollback(Exception):
    """Internal control flow: run() catches this and reloads the latest
    checkpoint (never escapes the trainer)."""

    def __init__(self, step: int):
        super().__init__(f"non-finite loss at step {step}")
        self.step = step


class PreemptionRequested(Exception):
    """Internal control flow: a preemption signal arrived; run() unwinds
    to its handler (never escapes the trainer)."""


class PreemptionExit(SystemExit):
    """Clean resumable exit after a preemption checkpoint.  A SystemExit
    subclass so an unhandled escape still exits the process with
    ``EXIT_PREEMPTED`` instead of a traceback."""

    def __init__(self, message: str):
        super().__init__(EXIT_PREEMPTED)
        self.message = message

    def __str__(self) -> str:
        return self.message


class Sentinel:
    """Host-side half of the non-finite guard (policy + bounded budgets).

    ``watch()`` is called once per train step with *lazy references* to
    the step's loss (and, under ``skip_batch``, the device guard's skip
    flag); ``check()`` materializes everything pending — callers invoke it
    at fenced boundaries where the values are already computed, so it
    costs device→host scalar pulls, never a sync.
    """

    def __init__(self, policy: str = "abort", max_skips: int = 8,
                 max_rollbacks: int = 2, telemetry=None):
        if policy not in POLICIES:
            raise ValueError(
                f"sentinel policy must be one of {POLICIES}, got {policy!r}")
        self.policy = policy
        self.max_skips = max_skips
        self.max_rollbacks = max_rollbacks
        self.telemetry = telemetry
        self.skips = 0.0          # cumulative skipped updates (skip_batch)
        self.rollbacks = 0        # maintained by the trainer
        self._pending: list[tuple[int, object, object]] = []

    @property
    def device_guard(self) -> bool:
        """Whether the compiled step must carry the finite-select guard."""
        return self.policy == "skip_batch"

    def watch(self, step: int, cost, skip_flag=None) -> None:
        self._pending.append((step, cost, skip_flag))

    def reset_pending(self) -> None:
        """Drop unmaterialized observations (after a rollback restored an
        older state, pending losses describe a discarded timeline)."""
        self._pending.clear()

    def check(self) -> None:
        """Materialize pending observations and enforce the policy.

        Raises :class:`NonFiniteLossError` (abort / budget exhausted) or
        :class:`SentinelRollback` (rollback policy).
        """
        pending, self._pending = self._pending, []
        for step, cost, skip_flag in pending:
            if skip_flag is not None:
                # device guard already protected the params; enforce budget
                n = float(np.max(np.asarray(skip_flag)))
                if n > 0:
                    self.skips += n
                    self._emit(SENTINEL_SKIP, step=step,
                               total_skips=self.skips)
                    print(f"sentinel: skipped non-finite update at step "
                          f"{step} ({self.skips:g}/{self.max_skips} budget)",
                          file=sys.stderr, flush=True)
                    if self.skips > self.max_skips:
                        raise NonFiniteLossError(
                            f"sentinel skip budget exhausted: "
                            f"{self.skips:g} skipped updates > "
                            f"max_skips={self.max_skips}", step=step)
                continue
            if cost is None:
                continue
            if bool(np.isfinite(np.asarray(cost)).all()):
                continue
            self._emit(SENTINEL_NONFINITE, step=step, policy=self.policy)
            if self.policy == "rollback":
                raise SentinelRollback(step)
            raise NonFiniteLossError(
                f"non-finite loss at step {step} (sentinel policy 'abort'; "
                f"use sentinel_policy=skip_batch/rollback to absorb "
                f"transients)", step=step)

    def _emit(self, name: str, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.instant(name, **fields)


class PreemptGuard:
    """Cooperative preemption-signal handler (main thread only).

    The handler itself only flips a flag and writes one stderr line —
    everything heavier (the final checkpoint, the resumable exit) happens
    in the run loop at a step boundary, where the training state is
    consistent.
    """

    def __init__(self, signals=(signal.SIGTERM,), telemetry=None):
        self.signals = tuple(signals)
        self.telemetry = telemetry
        self.triggered = False
        self._prev: dict[int, object] = {}
        self.installed = False

    def _handler(self, signum, frame) -> None:
        self.triggered = True
        # signal-safe-ish: one small write, no allocation-heavy work
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        sys.stderr.write(
            f"preempt: received {name}; will checkpoint and exit at the "
            f"next step boundary\n")

    def install(self) -> bool:
        """Install handlers; -> False (inactive) off the main thread,
        where ``signal.signal`` is illegal."""
        if threading.current_thread() is not threading.main_thread():
            return False
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._handler)
        self.installed = True
        return True

    def uninstall(self) -> None:
        if not self.installed:
            return
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self.installed = False
