"""Crash-safe shared event log inside ``resilience.json`` (ISSUE 5).

Two writers share that file: the supervisor rewrites the whole attempt
summary after every attempt, and the checkpoint recovery chain appends
``ckpt.quarantine`` / ``ckpt.fallback`` events from inside the training
process.  They never run concurrently (the supervisor only writes between
attempts), but each must preserve the other's records: this module owns
the ``events`` list — read-modify-write with the same ``os.replace``
crash-safety contract the summary uses — and the supervisor's summary
rewrite carries any existing ``events`` forward.

A leaf module (stdlib only): ``utils.checkpoint`` imports it without
pulling in the supervisor's subprocess machinery.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def read_events(path: str) -> list[dict]:
    """The ``events`` list of a resilience.json, or ``[]``."""
    data = _read(path)
    events = data.get("events")
    return events if isinstance(events, list) else []


def _read(path: str) -> dict:
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            loaded = json.load(f)
    except (OSError, ValueError) as e:
        print(f"resilience: unreadable {path} ({e}); starting a fresh "
              f"event list", file=sys.stderr, flush=True)
        return {}
    return loaded if isinstance(loaded, dict) else {}


def record_event(path: str, name: str, **fields) -> None:
    """Append one event, atomically rewriting the file.

    Best-effort by design: the chain records its fallback while actively
    recovering a run — a dead audit disk must not abort the recovery it is
    auditing (the failure is reported to stderr, never silently dropped).
    """
    data = _read(path)
    events = data.setdefault("events", [])
    # wall-clock stamp, not a duration: this is an audit record a human
    # correlates with scheduler logs
    events.append({"ts": time.time(), "name": name,  # lint: wall-ok — audit
                   **fields})           # stamp humans correlate with logs
    # per-writer tmp name: the scrubber CLI may quarantine against a
    # directory whose live writer thread is scrubbing too — a shared
    # ".tmp" would let one writer publish the other's half-written file.
    # The os.replace itself stays atomic; a lost UPDATE between two truly
    # simultaneous read-modify-writes remains possible and is accepted
    # for an advisory audit log (locking here could block a recovery)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1)
        os.replace(tmp, path)
    except OSError as e:
        print(f"resilience: could not record {name!r} in {path}: {e}",
              file=sys.stderr, flush=True)
