"""Deterministic, CPU-testable fault injection (ISSUE 4).

The reference stack had no fault story at all — a dead rank killed the
whole ``mpirun`` tree (SURVEY.md §4) and nothing could *rehearse* a crash.
Here every recovery path the resilience layer promises (supervisor
restart, sentinel policies, prefetch stall detection, checkpoint-writer
failure) is exercisable in tier-1 CPU tests through one deterministic
fault plan.

Grammar (``THEANOMPI_FAULT_PLAN`` env var or the ``fault_plan`` rule key;
specs separated by ``;`` or ``,``)::

    SITE:ACTION@INDEX[@ATTEMPT]

    step:raise@12        raise FaultInjected when train_iter reaches step 12
    step:kill@12@1       SIGKILL the process at step 12, attempt 1 only
    step:nan@12          poison step 12's batch with NaN (a real NaN loss,
                         so the sentinel's device guard sees the genuine
                         article, not a spoofed metric)
    prefetch:stall@3     the Prefetcher's source hangs before batch 3
                         (exercises stall_timeout / PrefetchStallError)
    prefetch:raise@3     the source iterator raises at batch 3
    data:torn_read@2     read_with_retry's 3rd read (ordinal 2) raises a
                         short-read ValueError once — the retry loop and
                         the DATA_RETRY telemetry counter are exercised
    data:stall@2         the 3rd data read hangs (a dead NFS mount) until
                         released (models.data.base.release_data_stalls)
                         or the reading thread/process is torn down
    checkpoint:fail@1    Checkpointer._write raises OSError for epoch 1
    checkpoint:truncate@1       ISSUE 5 corruption sites (ckpt_truncate /
    checkpoint:bitflip@1        ckpt_bitflip / ckpt_manifest_drop): damage
    checkpoint:manifest_drop@1  epoch 1's PUBLISHED files post-commit —
                         truncate the .npz to half, flip one mid-file byte,
                         or delete the manifest — so the verified recovery
                         chain (fallback, quarantine, exit 77) is
                         exercisable in tier-1 CPU tests
    reshard:fail@2       ISSUE 8: the elastic reshard planner fails on
                         supervisor attempt 2 (CheckpointReshardError ->
                         exit 79, which the supervisor classifies FATAL —
                         no restart loop over an unplannable transition)
    fleet:kill_job@1     ISSUE 11: SIGKILL the fleet scheduler's 2nd
                         launched child process (launch ordinal 1) — the
                         job's supervisor classifies a crash and restarts
                         it in place; the fleet sees one episode
    fleet:ledger_torn_write@1  the 2nd ledger persist (ordinal 1) tears
                         the main state file in half after commit — the
                         next load must recover from the previous
                         generation, not crash the scheduler
    serve:raise@6        ISSUE 14: raise FaultInjected at serving decode
                         step 6 (under ``tmserve --supervise`` the
                         replica supervisor classifies a crash and
                         restarts; the REQUESTS.jsonl terminal log makes
                         the restart skip already-answered requests)
    serve:stall@6        decode step 6 hangs for THEANOMPI_SERVE_STALL_S
                         seconds (default 2.0) — exercises the hang/SLO
                         health detectors against a wedged decode
    serve:rollout_corrupt@0    bit-flip the 1st rollout CANDIDATE's .npz
                         before the watcher verifies it — the rollout
                         must refuse the candidate and keep serving the
                         old weights (candidate ordinal, not decode step)
    easgd:worker_slow@2  ISSUE 20: sleep THEANOMPI_EASGD_SLOW_S seconds
                         (default 0.5) before the elastic exchange of
                         round ordinal 2 — a straggler stalling the
                         synchronous round: throughput degrades, the
                         exchange math is untouched
    gosgd:gossip_drop@2  the gossip round of ordinal 2 (rounds where a
                         push was drawn) skips its collective — the host
                         draws are still consumed, so the round schedule
                         stays aligned and only worker staleness grows

``INDEX`` is the global step for ``step``, the batch ordinal for
``prefetch``, the per-process read ordinal for ``data`` (every
``read_with_retry`` call draws the next ordinal; ``set_data_hooks``
resets the counter), the epoch for ``checkpoint``, the supervisor
attempt for ``reshard``, the launch/persist ordinal for ``fleet``, the
exchange/gossip round ordinal for ``easgd``/``gosgd``, and
for ``serve`` the decode-step ordinal (``raise``/``stall``) or the
rollout-candidate ordinal (``rollout_corrupt`` — the two hooks count
different things, so the scheduler and the rollout watcher both narrow
their ``fire`` calls by action).  The optional ``ATTEMPT``
gates a spec to one supervisor attempt (``THEANOMPI_ATTEMPT``, which the
supervisor sets; unsupervised processes count as attempt 1) — a ``kill``
spec under supervision should carry ``@1`` so the restarted attempt does
not re-die at the same step.  Each spec fires at most once per process.

Zero cost when absent: with no plan configured every injection point is a
single ``is None`` check.
"""

from __future__ import annotations

import os
import signal
import sys
from dataclasses import dataclass, field


class FaultInjected(RuntimeError):
    """An injected failure (never raised unless a fault plan asked for it)."""


class FaultPlanError(ValueError):
    """A fault-plan string that does not parse."""


#: valid actions per injection site
SITES = {
    "step": ("raise", "kill", "nan"),
    "prefetch": ("stall", "raise"),
    "data": ("torn_read", "stall"),
    "checkpoint": ("fail", "truncate", "bitflip", "manifest_drop"),
    "reshard": ("fail",),
    "fleet": ("kill_job", "ledger_torn_write"),
    "serve": ("raise", "stall", "rollout_corrupt"),
    "easgd": ("worker_slow",),
    "gosgd": ("gossip_drop",),
}


def current_attempt() -> int:
    """The supervisor attempt this process is (1 when unsupervised)."""
    try:
        return int(os.environ.get("THEANOMPI_ATTEMPT", "1"))
    except ValueError:
        return 1


@dataclass
class FaultSpec:
    site: str
    action: str
    index: int
    attempt: int | None = None
    fired: bool = field(default=False, compare=False)

    def matches(self, site: str, index: int,
                action: str | None = None) -> bool:
        return (
            not self.fired
            and self.site == site
            and self.index == int(index)
            and (action is None or self.action == action)
            and (self.attempt is None or self.attempt == current_attempt())
        )


class FaultPlan:
    """An ordered list of one-shot :class:`FaultSpec` entries."""

    def __init__(self, specs: list[FaultSpec]):
        self.specs = specs

    def __repr__(self) -> str:
        return f"FaultPlan({self.specs!r})"

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = []
        for raw in text.replace(";", ",").split(","):
            raw = raw.strip()
            if not raw:
                continue
            head, _, rest = raw.partition("@")
            site, _, action = head.partition(":")
            site, action = site.strip(), action.strip()
            if site not in SITES:
                raise FaultPlanError(
                    f"unknown fault site {site!r} in {raw!r} "
                    f"(sites: {', '.join(SITES)})"
                )
            if action not in SITES[site]:
                raise FaultPlanError(
                    f"action {action!r} invalid for site {site!r} in {raw!r} "
                    f"(valid: {', '.join(SITES[site])})"
                )
            if not rest:
                raise FaultPlanError(f"missing @INDEX in fault spec {raw!r}")
            parts = rest.split("@")
            if len(parts) > 2:
                raise FaultPlanError(f"too many '@' in fault spec {raw!r}")
            try:
                index = int(parts[0])
                attempt = int(parts[1]) if len(parts) == 2 else None
            except ValueError as e:
                raise FaultPlanError(
                    f"non-integer index/attempt in fault spec {raw!r}"
                ) from e
            specs.append(FaultSpec(site, action, index, attempt))
        if not specs:
            raise FaultPlanError(f"empty fault plan {text!r}")
        return cls(specs)

    @classmethod
    def from_spec(cls, spec: "str | FaultPlan | None") -> "FaultPlan | None":
        """Build from an explicit spec string, falling back to the
        ``THEANOMPI_FAULT_PLAN`` env var; None when neither is set."""
        if isinstance(spec, FaultPlan):
            return spec
        text = spec or os.environ.get("THEANOMPI_FAULT_PLAN")
        return cls.parse(text) if text else None

    def fire(self, site: str, index: int,
             action: str | None = None) -> str | None:
        """The action to inject at (site, index) now, or None.  Marks the
        matched spec fired so it cannot trigger twice in one process.
        ``action`` narrows the match to one action — for sites whose
        actions count DIFFERENT ordinals (``fleet``: launch ordinal for
        ``kill_job``, persist ordinal for ``ledger_torn_write``), so one
        hook's counter cannot consume the other hook's spec."""
        for s in self.specs:
            if s.matches(site, index, action):
                s.fired = True
                return s.action
        return None


def kill_self() -> None:
    """SIGKILL this process — the un-handleable death a preempted VM or an
    OOM-killer delivers; nothing downstream of this line runs."""
    print("faults: injected SIGKILL", file=sys.stderr, flush=True)
    os.kill(os.getpid(), signal.SIGKILL)
