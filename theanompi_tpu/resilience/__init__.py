"""theanompi_tpu.resilience — supervised training, fault injection,
health watchdog, non-finite sentinel, preemption handling (ISSUE 4).

The reference Theano-MPI stack had no fault story: one dead rank killed
the whole ``mpirun`` tree (SURVEY.md §4).  This package is the TPU
rebuild's robustness layer — the framework itself survives crashes,
preemptions, NaNs and hangs:

- :mod:`supervisor` — child-process auto-restart loop with exit
  classification, bounded exponential backoff + jitter, checkpoint
  auto-resume, and a ``resilience.json`` audit trail
  (``tmlauncher --supervise``);
- :mod:`faults` — the deterministic fault plan
  (``THEANOMPI_FAULT_PLAN`` / ``fault_plan`` rule key) that makes every
  recovery path exercisable in CPU tier-1 tests;
- :mod:`watchdog` — heartbeat file + median-adaptive stall detector;
- :mod:`sentinel` — non-finite loss/grad guard (abort / skip_batch /
  rollback) and cooperative SIGTERM preemption handling.

Everything is **off by default**: a run without ``--supervise``, without
resilience rule keys and without the env vars makes no behavioral change
to any existing entry path (locked by tests).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from theanompi_tpu.resilience.supervisor import (  # noqa: F401
    EXIT_CKPT,
    EXIT_CLEAN,
    EXIT_CONFIG,
    EXIT_CRASH,
    EXIT_HANG,
    EXIT_PREEMPTED,
    EXIT_RESHARD,
    JobResult,
    Supervisor,
    classify_exit,
    probe_device_count,
    run_job,
)
from theanompi_tpu.resilience.events import (  # noqa: F401
    read_events,
    record_event,
)
from theanompi_tpu.resilience.faults import (  # noqa: F401
    FaultInjected,
    FaultPlan,
    FaultPlanError,
)
from theanompi_tpu.resilience.sentinel import (  # noqa: F401
    NonFiniteLossError,
    PreemptGuard,
    PreemptionExit,
    PreemptionRequested,
    Sentinel,
    SentinelRollback,
)
from theanompi_tpu.resilience.watchdog import (  # noqa: F401
    Heartbeat,
    Watchdog,
    heartbeat_age_s,
)


def supervised() -> bool:
    """Whether this process runs under a :class:`Supervisor`."""
    return os.environ.get("THEANOMPI_SUPERVISED") == "1"


@dataclass
class ResilienceConfig:
    """Per-trainer resilience knobs, resolved from the rule config + env.

    Every field's default means OFF (or supervisor-auto): a default
    instance created for a bare trainer changes nothing unless the
    supervisor env vars (``THEANOMPI_SUPERVISED`` / ``THEANOMPI_HEARTBEAT``
    / ``THEANOMPI_FAULT_PLAN``) are present.
    """

    fault_plan: str | None = None            # faults.FaultPlan grammar
    sentinel_policy: str | None = None       # None=off | abort|skip_batch|rollback
    sentinel_max_skips: int = 8
    sentinel_max_rollbacks: int = 2
    watchdog: bool | None = None             # None=auto: on iff heartbeat set
    watchdog_multiple: float = 10.0
    watchdog_min_s: float = 30.0
    watchdog_poll_s: float = 1.0
    heartbeat_path: str | None = None        # None: THEANOMPI_HEARTBEAT env
    handle_preemption: bool | None = None    # None=auto: on iff supervised
    prefetch_stall_timeout: float | None = None

    #: rule-config keys consumed by :meth:`from_rule_config`
    KEYS = ("fault_plan", "sentinel_policy", "sentinel_max_skips",
            "sentinel_max_rollbacks", "watchdog", "watchdog_multiple",
            "watchdog_min_s", "watchdog_poll_s", "heartbeat_path",
            "handle_preemption", "prefetch_stall_timeout")

    @classmethod
    def from_rule_config(cls, config: dict) -> "ResilienceConfig":
        return cls(**{k: config[k] for k in cls.KEYS if k in config})

    # -- resolution (config beats env; env is the supervisor's channel) ------
    def resolved_heartbeat_path(self) -> str | None:
        return self.heartbeat_path or os.environ.get("THEANOMPI_HEARTBEAT")

    def watchdog_enabled(self) -> bool:
        if self.watchdog is not None:
            return bool(self.watchdog)
        return self.resolved_heartbeat_path() is not None

    def preemption_enabled(self) -> bool:
        if self.handle_preemption is not None:
            return bool(self.handle_preemption)
        return supervised()

    # -- builders (lazy: a disabled feature imports/allocates nothing) -------
    def build_fault_plan(self) -> FaultPlan | None:
        return FaultPlan.from_spec(self.fault_plan)

    def build_sentinel(self, telemetry=None) -> Sentinel | None:
        if self.sentinel_policy is None:
            return None
        return Sentinel(policy=self.sentinel_policy,
                        max_skips=int(self.sentinel_max_skips),
                        max_rollbacks=int(self.sentinel_max_rollbacks),
                        telemetry=telemetry)

    def build_heartbeat(self) -> Heartbeat | None:
        """The liveness file writer alone — for when the in-process stall
        DETECTOR is disabled (``watchdog=False``) but a supervisor still
        watches the heartbeat file (``--hang-timeout`` backstop): turning
        off the detector must not silence liveness reporting, or the
        backstop would kill every healthy child at the timeout."""
        path = self.resolved_heartbeat_path()
        return Heartbeat(path) if path else None

    def build_watchdog(self, telemetry=None) -> Watchdog | None:
        if not self.watchdog_enabled():
            return None
        hb_path = self.resolved_heartbeat_path()
        heartbeat = Heartbeat(hb_path) if hb_path else None
        return Watchdog(
            multiple=float(self.watchdog_multiple),
            min_timeout_s=float(self.watchdog_min_s),
            poll_s=float(self.watchdog_poll_s),
            heartbeat=heartbeat,
            telemetry=telemetry,
            # an unsupervised user's run is warned, never self-killed
            escalate="exit" if supervised() else "warn",
            exit_code=EXIT_HANG,
        )
