"""Declared import-layering DAG + the ``import-dag`` rule.

PR 6 drew one wall (serving never imports training machinery) as a
hand-rolled test.  This module generalizes it: every module in the
package is assigned to a named LAYER (longest-prefix match), and the
DAG below declares which lower layers each layer may import at module
level.  The declaration is acyclic BY CONSTRUCTION — an allowed-set may
only reference layers declared earlier in the ordered list, which
:func:`validate_dag` enforces (and a test locks).

Two kinds of check:

- **layering** (module-level imports only): a top-of-module import is an
  import-time dependency; it must point at the same layer or one the
  declaration allows.  Function-local imports are deliberate lazy edges
  (the repo's cycle-breaking idiom — e.g. ``ops/opt.py`` lazily pulling
  ``parallel.tensor``) and are exempt from layering.
- **walls** (ANY-depth imports): the hard boundaries no lazy import may
  cross — serving must never touch training machinery even lazily, and
  the bottom layers stay (near-)leaves so everything above can depend
  on them without cycles: telemetry imports nothing in-package, and
  resilience reaches only down (codes, telemetry — relaxed in ISSUE 13
  so the watchdog/sentinel emit through the registered names in
  ``telemetry/metrics.py``).  ``resilience/codes.py`` staying
  import-free is what lets both halves of the supervisor share it; the
  companion ``exit-code`` rule keeps it the only source of exit codes.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from theanompi_tpu.analysis.core import (
    REPO_ROOT,
    SEV_ERROR,
    Finding,
    Rule,
    SourceFile,
    register,
)

PKG = "theanompi_tpu"

#: The layer DAG, bottom-up.  Each entry: (layer, module prefixes,
#: allowed lower layers).  Assignment is by LONGEST matching prefix, so
#: ``resilience.codes`` lands in ``codes`` even though ``resilience``
#: also matches; the bare ``theanompi_tpu`` prefix makes ``tooling`` the
#: default for new top-level modules.  In-layer imports are always
#: allowed.
LAYER_DAG: tuple[tuple[str, tuple[str, ...], tuple[str, ...]], ...] = (
    # the interleave harness is stdlib-only sync-points (ISSUE 15) —
    # a bottom layer like codes, so the instrumented seams (telemetry
    # ticker, checkpoint writer, fleet passes) may import sp() without
    # puncturing their walls; longest-prefix assignment peels it off
    # the analysis layer above
    ("syncpoint",  (f"{PKG}.analysis.interleave",), ()),
    ("codes",      (f"{PKG}.resilience.codes",), ()),
    # the durable serving file contracts (queue.jsonl / REQUESTS.jsonl /
    # SERVE_SNAPSHOT.json, ISSUE 19) are stdlib-only — a bottom layer,
    # peeled off ``serving`` by longest-prefix so the ROUTER may speak
    # the wire format without importing the engine/scheduler machinery
    ("serve_lifecycle", (f"{PKG}.serving.lifecycle",), ()),
    ("native",     (f"{PKG}.native",), ()),
    ("telemetry",  (f"{PKG}.telemetry",), ("syncpoint",)),
    ("resilience", (f"{PKG}.resilience",), ("codes", "telemetry")),
    ("mesh",       (f"{PKG}.parallel.mesh",), ()),
    ("kernels",    (f"{PKG}.ops.initializers", f"{PKG}.ops.layers",
                    f"{PKG}.ops.losses", f"{PKG}.ops.quant",
                    f"{PKG}.ops.pallas_attention",
                    f"{PKG}.ops.pallas_paged_attention"),
                   ("mesh",)),
    ("sharding",   (f"{PKG}.parallel.tensor", f"{PKG}.parallel.ring_attention",
                    f"{PKG}.parallel.pipeline"),
                   ("mesh", "kernels")),
    ("ops",        (f"{PKG}.ops",), ("mesh", "kernels", "sharding")),
    ("utils_base", (f"{PKG}.utils.helper_funcs", f"{PKG}.utils.recorder",
                    f"{PKG}.utils.divergence"),
                   ("mesh",)),
    ("exchange",   (f"{PKG}.parallel.exchanger", f"{PKG}.parallel.overlap"),
                   ("mesh", "kernels")),
    ("data",       (f"{PKG}.models.data",),
                   ("codes", "resilience", "utils_base")),
    ("models",     (f"{PKG}.models",),
                   ("mesh", "kernels", "sharding", "ops", "utils_base",
                    "exchange", "data")),
    ("ckpt",       (f"{PKG}.utils.checkpoint",),
                   ("syncpoint", "codes", "telemetry", "resilience",
                    "utils_base")),
    # the async rules (parallel.easgd / parallel.gosgd, ISSUE 20) live
    # in this layer: their host-side state (round ordinals, gossip draws
    # via models.data.base.derive_seed, fault-plan hooks) imports only
    # downward — and they stay forbidden any-depth in the serving/fleet/
    # router walls below like the rest of the training machinery
    ("training",   (f"{PKG}.parallel",),
                   ("codes", "telemetry", "resilience", "mesh", "kernels",
                    "sharding", "ops", "utils_base", "exchange", "data",
                    "models", "ckpt")),
    ("tooling",    (f"{PKG}.launcher", f"{PKG}.utils", PKG),
                   ("codes", "native", "telemetry", "resilience", "mesh",
                    "kernels", "sharding", "ops", "utils_base", "exchange",
                    "data", "models", "ckpt", "training")),
    # the fleet scheduler runs training JOBS as subprocesses — it must
    # never import the training (or serving) machinery it supervises;
    # its world is exit codes, the run_job seam, fault plans, telemetry
    ("fleet",      (f"{PKG}.fleet",),
                   ("syncpoint", "codes", "telemetry", "resilience",
                    "utils_base")),
    # the router composes serving REPLICAS as fleet jobs (ISSUE 19): its
    # world is the fleet scheduler, the durable lifecycle file contracts,
    # exit codes and telemetry — the serving engine/scheduler machinery
    # and training both stay subprocesses (any-depth wall below)
    ("router",     (f"{PKG}.router",),
                   ("syncpoint", "codes", "serve_lifecycle", "telemetry",
                    "resilience", "utils_base", "fleet")),
    # serving is a read-only consumer: kernels (shared int8 wire format),
    # verified checkpoint loads, telemetry, the launcher's config surface
    # — NEVER exchange/training (see the any-depth wall below).
    # "resilience" admitted in ISSUE 14 for the FAULT GRAMMAR + exit
    # codes only; the supervisor/sentinel/watchdog machinery stays
    # walled off any-depth below
    ("serving",    (f"{PKG}.serving",),
                   ("codes", "serve_lifecycle", "telemetry", "kernels",
                    "utils_base", "ckpt", "tooling", "resilience")),
    ("analysis",   (f"{PKG}.analysis",),
                   ("syncpoint", "codes", "serve_lifecycle", "native",
                    "telemetry", "resilience", "mesh",
                    "kernels", "sharding", "ops", "utils_base", "exchange",
                    "data", "models", "ckpt", "training", "tooling",
                    "fleet", "router", "serving")),
)

#: training-side modules serving must never import at ANY depth (PR 6's
#: wall): a gradient, optimizer, exchanger or supervisor import there
#: means training machinery leaked into the inference path
SERVING_FORBIDDEN_IMPORTS = (
    f"{PKG}.parallel.trainer",
    f"{PKG}.parallel.bsp",
    f"{PKG}.parallel.easgd",
    f"{PKG}.parallel.gosgd",
    f"{PKG}.parallel.exchanger",
    f"{PKG}.parallel.pipeline",
    f"{PKG}.ops.opt",
    f"{PKG}.resilience.supervisor",
    f"{PKG}.resilience.sentinel",
    f"{PKG}.resilience.watchdog",
    # NOTE (ISSUE 14): ``resilience.faults`` was deliberately REMOVED from
    # this wall — the serving chaos sites (serve:raise/stall/
    # rollout_corrupt) fire inside the serving process, and the fault
    # grammar is leaf machinery (stdlib-only), not training machinery.
    # The supervisor half stays forbidden: ``tmserve --supervise`` reaches
    # ``run_job`` through ``resilience/replica.py`` (a resilience-layer
    # module) via a lazy import, mirroring the launcher seam.
    # serving ⊥ fleet (ISSUE 11): a replica must not reach into the
    # scheduler that may be preempting it — coordination flows the other
    # way, through processes and exit codes
    f"{PKG}.fleet",
    # serving ⊥ router (ISSUE 19), same shape: a replica must not reach
    # into the router that balances/drains it — it reads queue.jsonl and
    # writes REQUESTS.jsonl/SERVE_SNAPSHOT.json, nothing more
    f"{PKG}.router",
)

#: the mirror half of the serving ⊥ fleet wall, any depth: the scheduler
#: composes training JOBS as subprocesses; importing the machinery it
#: supervises (even lazily) would couple its process lifetime to a jax
#: runtime it exists to babysit
FLEET_FORBIDDEN_IMPORTS = (
    f"{PKG}.serving",
    f"{PKG}.parallel",
    f"{PKG}.models",
    f"{PKG}.ops",
    f"{PKG}.launcher",
    # fleet ⊥ router (ISSUE 19): the scheduler does not know replicas
    # exist — the router submits serving JobSpecs downward, never the
    # reverse
    f"{PKG}.router",
)

#: the router's world (ISSUE 19) is fleet jobs + the durable lifecycle
#: file contracts + telemetry/codes: the serving engine/scheduler
#: machinery and the training stack always run in replica/training
#: SUBPROCESSES.  Any-depth, like the serving wall — a lazy engine
#: import in the router would couple the balancing loop's lifetime to a
#: jax runtime it exists to supervise.  ``serving.lifecycle`` is the one
#: serving module the router may touch (the stdlib-only wire format);
#: the supervisor machinery is reached only through the fleet layer's
#: run_job seam, never directly.
ROUTER_FORBIDDEN_IMPORTS = (
    f"{PKG}.parallel",
    f"{PKG}.models",
    f"{PKG}.ops",
    f"{PKG}.launcher",
    f"{PKG}.serving.engine",
    f"{PKG}.serving.scheduler",
    f"{PKG}.serving.kv_cache",
    f"{PKG}.serving.prefix_cache",
    f"{PKG}.serving.rollout",
    f"{PKG}.serving.quant",
    f"{PKG}.serving.cli",
    f"{PKG}.resilience.supervisor",
    f"{PKG}.resilience.sentinel",
    f"{PKG}.resilience.watchdog",
)

#: subpackages that must stay import leaves at ANY depth: everything
#: above depends on them, so even a lazy upward import risks a cycle
#: (and telemetry in particular must stay importable before jax init)
LEAF_SUBPACKAGES = {
    # telemetry may additionally reach the stdlib-only sync-point module
    # (ISSUE 15: the health ticker is an instrumented seam) — interleave
    # imports nothing in-package, so the leaf stays cycle-free.  ISSUE
    # 16's profile.py (step attribution), ledger.py (perf trajectory) and
    # prof.py (the tmprof CLI) live INSIDE this leaf: they import only
    # telemetry siblings, so the wall holds unchanged
    f"{PKG}.telemetry": (f"{PKG}.telemetry", f"{PKG}.analysis.interleave"),
    # resilience may reach telemetry (ISSUE 13: registered event names +
    # the watchdog's flight-recorder dump) — still downward-only, so the
    # no-cycles property holds: telemetry itself stays a strict leaf
    f"{PKG}.resilience": (f"{PKG}.resilience", f"{PKG}.telemetry"),
    f"{PKG}.native": (f"{PKG}.native",),
}


def validate_dag() -> None:
    """Raise if the declaration is not a DAG (an allowed-set referencing
    a later or unknown layer) or a layer name repeats."""
    seen: list[str] = []
    for layer, prefixes, allowed in LAYER_DAG:
        if layer in seen:
            raise ValueError(f"duplicate layer {layer!r}")
        for ref in allowed:
            if ref not in seen:
                raise ValueError(
                    f"layer {layer!r} allows {ref!r}, which is not "
                    f"declared EARLIER — the declaration must stay "
                    f"acyclic by construction")
        if not prefixes:
            raise ValueError(f"layer {layer!r} has no module prefixes")
        seen.append(layer)


def module_layer(module: str) -> str | None:
    """Layer of a dotted module name, by longest matching prefix."""
    best, best_len = None, -1
    for layer, prefixes, _ in LAYER_DAG:
        for p in prefixes:
            if (module == p or module.startswith(p + ".")) \
                    and len(p) > best_len:
                best, best_len = layer, len(p)
    return best


def _allowed(layer: str) -> tuple[str, ...]:
    for name, _, allowed in LAYER_DAG:
        if name == layer:
            return allowed
    raise KeyError(layer)


def _package_modules(root: str) -> set[str]:
    """Every real dotted module name under the package (used to resolve
    ``from pkg import sub`` to ``pkg.sub`` only when sub IS a module)."""
    mods = set()
    pkg_dir = os.path.join(root, PKG)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames.sort()
        for f in filenames:
            if not f.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, f), root)
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            mods.add(mod)
    return mods


def _file_module(rel: str) -> str | None:
    """Dotted module name of a repo-relative path, None outside the
    package (bench.py etc. carry no layer)."""
    if not rel.startswith(PKG + "/") or not rel.endswith(".py"):
        return None
    mod = rel[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _resolve_from(module: str, names: list[str], known: set[str]) -> set[str]:
    out = set()
    for n in names:
        full = f"{module}.{n}"
        out.add(full if full in known else module)
    return out


def _module_level_imports(tree: ast.Module, known: set[str]
                          ) -> Iterator[tuple[int, str]]:
    """In-package imports reachable at import time: top-level statements,
    descending through ``try``/``if``/``with`` wrappers (the version-
    probe idiom) and class bodies (which ALSO execute at import time)
    but NOT into function bodies — a function-local import is a
    deliberate lazy edge."""
    stack: list[ast.stmt] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Try, ast.If, ast.ClassDef,
                             ast.With, ast.AsyncWith)):
            stack.extend(node.body)
            stack.extend(getattr(node, "orelse", ()))
            for h in getattr(node, "handlers", ()):
                stack.extend(h.body)
            stack.extend(getattr(node, "finalbody", ()))
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith(PKG):
                    yield node.lineno, a.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.module.startswith(PKG):
            for mod in sorted(_resolve_from(
                    node.module, [a.name for a in node.names], known)):
                yield node.lineno, mod


def _all_imports(tree: ast.Module) -> Iterator[tuple[int, str]]:
    """Every in-package module an import touches, at any depth.  For
    ``from pkg import name`` both ``pkg`` and ``pkg.name`` are yielded —
    the wall must catch submodule binds without needing resolution."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith(PKG):
                    yield node.lineno, a.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.module.startswith(PKG):
            yield node.lineno, node.module
            for a in node.names:
                yield node.lineno, f"{node.module}.{a.name}"


def _under(mod: str, prefix: str) -> bool:
    return mod == prefix or mod.startswith(prefix + ".")


@register
class ImportDagRule(Rule):
    """Package layering: module-level imports obey the declared DAG;
    hard walls hold at any depth.

    The declaration lives in :data:`LAYER_DAG` (this module's
    docstring explains the two check kinds).  A deliberate one-off
    exception marks the import line ``lint: import-dag-ok — <why>`` —
    but prefer moving the symbol to the layer that owns it.
    """

    name = "import-dag"
    severity = SEV_ERROR
    description = ("declared package-layer DAG (module-level) + any-depth "
                   "walls: serving⊥training, leaf subpackages stay leaves")

    _known_cache: dict[str, set[str]] = {}

    def _known(self, root: str) -> set[str]:
        if root not in self._known_cache:
            self._known_cache[root] = _package_modules(root)
        return self._known_cache[root]

    def check(self, src: SourceFile) -> Iterator[Finding]:
        validate_dag()
        mod = _file_module(src.rel)
        if mod is None:
            return
        root = src.path[: -len(src.rel) - 1] if src.path.endswith(src.rel) \
            else REPO_ROOT
        known = self._known(root)
        layer = module_layer(mod)
        if layer is None:
            yield self.finding(
                src, 1, 0,
                f"module {mod} is not assigned to any layer in "
                f"analysis/layers.py — declare its place in the DAG")
            return
        allowed = set(_allowed(layer))
        for lineno, imp in _module_level_imports(src.tree, known):
            if _under(imp, mod):
                continue
            tgt = module_layer(imp)
            if tgt is None or tgt == layer or tgt in allowed:
                continue
            yield self.finding(
                src, lineno, 0,
                f"layer {layer!r} ({mod}) imports {imp} (layer {tgt!r}) "
                f"at module level — not in its declared allowed set "
                f"{sorted(allowed)}")
        # -- any-depth walls -------------------------------------------------
        if _under(mod, f"{PKG}.serving"):
            for lineno, imp in _all_imports(src.tree):
                if any(_under(imp, bad) for bad in SERVING_FORBIDDEN_IMPORTS):
                    yield self.finding(
                        src, lineno, 0,
                        f"serving imports training machinery {imp} — the "
                        f"inference path must stay a read-only consumer")
        if _under(mod, f"{PKG}.fleet"):
            for lineno, imp in _all_imports(src.tree):
                if any(_under(imp, bad) for bad in FLEET_FORBIDDEN_IMPORTS):
                    yield self.finding(
                        src, lineno, 0,
                        f"fleet imports {imp} — the scheduler supervises "
                        f"training/serving as subprocesses and must never "
                        f"import that machinery, even lazily")
        if _under(mod, f"{PKG}.router"):
            for lineno, imp in _all_imports(src.tree):
                if any(_under(imp, bad) for bad in ROUTER_FORBIDDEN_IMPORTS):
                    yield self.finding(
                        src, lineno, 0,
                        f"router imports {imp} — replicas and training are "
                        f"subprocesses; the router speaks only the durable "
                        f"lifecycle file contracts and the fleet job seam")
        for leaf, ok_prefixes in LEAF_SUBPACKAGES.items():
            if not _under(mod, leaf):
                continue
            for lineno, imp in _all_imports(src.tree):
                if imp.startswith(PKG) and not any(
                        _under(imp, p) for p in ok_prefixes):
                    yield self.finding(
                        src, lineno, 0,
                        f"{leaf} is a leaf subpackage (everything above "
                        f"depends on it) but imports {imp}")
