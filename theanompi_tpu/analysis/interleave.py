"""Deterministic interleaving harness: sync-points + schedule replay.

Every concurrency bug this repo has shipped (the PR 5 torn async
snapshot, the PR 10 ``on_supervisor`` registration race) was found by
accident — a lucky CI timing, a user report — because thread
interleavings are the one input the test suite never controlled.  This
module makes them an input.

The contract mirrors ``resilience/faults.py``: production code is
instrumented with named **sync-points**, ``sp("ckpt.write.publish")``,
which cost a single ``is None`` check when no schedule is armed — the
instrumented seams (checkpoint writer, fleet scheduler passes, health
ticker) pay nothing in real runs.  A test arms an :class:`Interleaver`
with an explicit ordering of sync-point names; each thread reaching a
scheduled point blocks until its name is at the head of the order, so
one schedule == one exact interleaving, replayable bit-stably.

Two schedule generators:

- :func:`schedules` — raw permutations of a name list (seeded sample
  when the full factorial exceeds ``limit``).
- :func:`interleavings` — order-preserving merges of per-thread chains;
  every generated schedule respects each thread's program order, so
  none of them can deadlock the harness.  This is the right generator
  for real seams, where each thread's points are sequenced.

Infeasible orderings (a head no thread can reach, e.g. a raw
permutation that puts a thread's second point before its first) do not
hang: a blocked waiter times out and the stuck head is dropped as
``skipped``, deterministically, so every schedule terminates with a
recorded trace.

Negative proof (the ``hlo_audit`` philosophy): :func:`race_audit` runs
a seeded lost-update race (:class:`RacyCounter`) and its lock-guarded
twin (:class:`GuardedCounter`) under every 2-thread interleaving and
raises :class:`RaceAuditError` unless the race is detected AND the
guarded twin stays clean — if the harness ever stops catching the bug
it was built for, ``tmlint --race-audit`` exits 1.

This module is deliberately stdlib-only (``threading`` + ``math`` +
``random``): it sits at the *bottom* of the import DAG (see
``layers.LAYER_DAG``) so leaf subpackages like telemetry may import it.
"""

from __future__ import annotations

import itertools
import math
import random
import threading
import time

__all__ = [
    "sp", "arm", "disarm", "Interleaver", "schedules", "interleavings",
    "RacyCounter", "GuardedCounter", "race_audit", "RaceAuditError",
    "RACE_CHAINS",
]

#: the armed schedule, or None.  Read without a lock: ``sp`` must cost a
#: single attribute load + is-None test in production (the faults.py
#: zero-cost contract); arming happens only in tests, via ``with``.
_ARMED: "Interleaver | None" = None


def sp(name: str) -> None:
    """Sync-point: no-op unless a schedule is armed (zero cost: one
    ``is None`` check), else block until ``name`` reaches the head of
    the armed order."""
    s = _ARMED
    if s is not None:
        s.reach(name)


def arm(interleaver: "Interleaver") -> None:
    global _ARMED
    if _ARMED is not None:
        raise RuntimeError("an Interleaver is already armed")
    _ARMED = interleaver


def disarm() -> None:
    global _ARMED
    _ARMED = None


class Interleaver:
    """One explicit interleaving: a list of sync-point names, granted
    strictly in order.

    Threads reaching a name not in the (remaining) order pass through
    untouched — instrumented code outside the scheduled window never
    blocks.  A thread reaching a scheduled name waits until that name
    is the head; if the head goes unclaimed for ``timeout_s`` (nobody
    can reach it — an infeasible ordering, or the seam simply never
    fires it) the head is dropped as ``skipped`` and the schedule moves
    on, so every schedule terminates.  ``trace`` records the realized
    history as ``(name, "granted" | "skipped")`` pairs.

    Use as a context manager to arm/disarm around the scheduled window::

        with Interleaver(["a.load", "b.load", "a.store", "b.store"]):
            ... start threads, join them ...
    """

    def __init__(self, order, timeout_s: float = 2.0):
        self.order: list[str] = [str(n) for n in order]
        self.timeout_s = float(timeout_s)
        self.trace: list[tuple[str, str]] = []
        self._cond = threading.Condition()

    def reach(self, name: str) -> None:
        with self._cond:
            if name not in self.order:
                return
            head = self.order[0]
            deadline = time.monotonic() + self.timeout_s
            while self.order and self.order[0] != name:
                if self.order[0] != head:
                    # the head changed — progress happened; reset the
                    # clock so only a genuinely stuck head gets dropped
                    head = self.order[0]
                    deadline = time.monotonic() + self.timeout_s
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    dropped = self.order.pop(0)
                    self.trace.append((dropped, "skipped"))
                    self._cond.notify_all()
                    if name not in self.order:
                        return
                    head = self.order[0] if self.order else None
                    deadline = time.monotonic() + self.timeout_s
                    continue
                self._cond.wait(min(remaining, 0.05))
                if name not in self.order:
                    return  # our entry was skipped by another waiter
            if self.order and self.order[0] == name:
                self.order.pop(0)
                self.trace.append((name, "granted"))
                self._cond.notify_all()

    def __enter__(self) -> "Interleaver":
        arm(self)
        return self

    def __exit__(self, *exc) -> None:
        disarm()


def schedules(points, limit: int | None = 24, seed: int = 0):
    """Deterministic orderings of ``points``: all permutations when the
    factorial fits ``limit``, else a seeded unranked sample of exactly
    ``limit`` distinct permutations.  Same (points, limit, seed) ->
    same list, always — schedules are test inputs and must be stable."""
    pts = list(points)
    total = math.factorial(len(pts))
    if limit is None or total <= limit:
        return [list(p) for p in itertools.permutations(pts)]
    rng = random.Random(seed)
    return [_perm_at(pts, k) for k in sorted(rng.sample(range(total), limit))]


def _perm_at(items, k: int) -> list:
    """The k-th permutation of ``items`` in lexicographic index order."""
    pool = list(items)
    out = []
    for i in range(len(pool), 0, -1):
        f = math.factorial(i - 1)
        j, k = divmod(k, f)
        out.append(pool.pop(j))
    return out


def interleavings(chains, limit: int | None = None, seed: int = 0):
    """Order-preserving merges of per-thread sync-point chains.

    ``chains`` is a sequence of name lists, one per thread, each in that
    thread's program order.  Every returned schedule keeps each chain's
    internal order, so a feasible execution exists for all of them — no
    skipped heads, no timeout waits.  All merges when the multinomial
    count fits ``limit``, else a seeded unranked sample."""
    chains = [list(c) for c in chains if c]
    total = _merge_count([len(c) for c in chains])
    if limit is None or total <= limit:
        return [_merge_at(chains, k) for k in range(total)]
    rng = random.Random(seed)
    return [_merge_at(chains, k)
            for k in sorted(rng.sample(range(total), limit))]


def _merge_count(lens) -> int:
    n = sum(lens)
    out = math.factorial(n)
    for ln in lens:
        out //= math.factorial(ln)
    return out


def _merge_at(chains, k: int) -> list[str]:
    """The k-th merge in the order induced by always counting chain-0
    continuations first (a mixed-radix unranking; bijective, so sampled
    indices give distinct schedules)."""
    pos = [0] * len(chains)
    out = []
    remaining = [len(c) for c in chains]
    while any(r for r in remaining):
        for i, c in enumerate(chains):
            if not remaining[i]:
                continue
            remaining[i] -= 1
            below = _merge_count(remaining)
            remaining[i] += 1
            if k < below:
                out.append(c[pos[i]])
                pos[i] += 1
                remaining[i] -= 1
                break
            k -= below
    return out


# -- seeded synthetic race (the negative proof) ------------------------------

class RacyCounter:
    """Deliberately unguarded read-modify-write — the exact lost-update
    shape of the PR 10 registration race.  Exists so :func:`race_audit`
    can prove the harness still *detects* races; never use in product
    code."""

    def __init__(self):
        self.value = 0

    def bump(self, label: str) -> None:
        sp(f"{label}.load")
        v = self.value
        sp(f"{label}.store")
        self.value = v + 1


class GuardedCounter:
    """The fixed twin: same sync-point alphabet, RMW under a lock.  Its
    job in :func:`race_audit` is the false-positive check — a harness
    that 'detects' a race here is broken."""

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def bump(self, label: str) -> None:
        # points stay OUTSIDE the lock: a scheduled wait while holding
        # the lock would stall the peer thread into timeout skips
        sp(f"{label}.load")
        sp(f"{label}.store")
        with self._lock:
            self.value += 1


#: per-thread sync-point chains of the two-bumper race scenario
RACE_CHAINS = (("a.load", "a.store"), ("b.load", "b.store"))


class RaceAuditError(AssertionError):
    """The interleaving harness lost its teeth (seeded race undetected)
    or grew false ones (guarded twin 'races').  Carries the audit
    counters as ``.report``."""

    def __init__(self, msg: str, report: dict | None = None):
        super().__init__(msg)
        self.report = report


def _run_counter(cls, order, timeout_s: float) -> int:
    c = cls()
    threads = [threading.Thread(target=c.bump, args=(lbl,),
                                name=f"interleave-{lbl}")
               for lbl, _ in (("a", 0), ("b", 0))]
    with Interleaver(order, timeout_s=timeout_s):
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    return c.value


def race_audit(limit: int | None = None, timeout_s: float = 2.0,
               seed: int = 0) -> dict:
    """Self-check the harness against the seeded race; -> audit report.

    Runs every order-preserving interleaving of :data:`RACE_CHAINS`
    over both counters.  Healthy means the racy twin loses at least one
    update (detection works) and the guarded twin never does (no false
    positives); anything else raises :class:`RaceAuditError`.
    """
    orders = interleavings(RACE_CHAINS, limit=limit, seed=seed)
    racy_lost = sum(1 for o in orders
                    if _run_counter(RacyCounter, o, timeout_s) != 2)
    guarded_lost = sum(1 for o in orders
                       if _run_counter(GuardedCounter, o, timeout_s) != 2)
    report = {
        "orderings": len(orders),
        "racy_lost_updates": racy_lost,
        "guarded_lost_updates": guarded_lost,
        "detected": racy_lost > 0,
    }
    if racy_lost == 0:
        raise RaceAuditError(
            "interleave audit: seeded lost-update race was NOT detected in "
            f"any of {len(orders)} orderings — the harness lost its teeth",
            report)
    if guarded_lost:
        raise RaceAuditError(
            f"interleave audit: lock-guarded twin lost updates in "
            f"{guarded_lost}/{len(orders)} orderings — false positive",
            report)
    return report
