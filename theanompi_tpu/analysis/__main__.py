"""``python -m theanompi_tpu.analysis`` == ``tmlint``."""

from theanompi_tpu.analysis.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
