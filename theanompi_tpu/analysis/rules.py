"""tmlint rules: the bug classes this repo has actually hit.

Three rules are straight ports of the PR 1/4/5 test lints (``wall``,
``swallow``, ``np-load``); four are new, distilled from the repo's own
incident history:

- ``donated-escape`` — PR 5's latent async-writer race: ``np.asarray`` on
  a jax array is ZERO-COPY on the CPU backend, so a view that crosses a
  return/thread/queue boundary aliases a buffer the next donated step
  will rewrite underneath the reader (torn .npz, flaky CRC).
- ``host-sync`` — PR 2's hoisting lesson: ``float()``/``bool()``/
  ``np.asarray``/``.item()`` on device values inside a telemetry span
  forces a device sync inside the timed region, so the span measures the
  sync it caused.
- ``jit-nondet`` — wall clocks and global RNG inside a jitted function
  burn a trace-time constant into the executable (different on every
  recompile, invisible at runtime); in the fault plan they break the
  PR 4 determinism contract outright.
- ``exit-code`` — PR 4's exit-code drift: bare 70/75/76/77/78/79 literals
  outside ``resilience/codes.py`` re-create the duplicated contract that
  module exists to kill.
- ``data-determinism`` — ISSUE 10's resume contract: one unseeded
  ``np.random.*`` draw in ``models/data/`` makes batch content depend on
  call order, which a mid-epoch cursor fast-forward cannot reproduce.
- ``telemetry-registered-names`` — ISSUE 13's health detectors and the
  fleet aggregator key on event names; a string-literal name at an
  emission site in ``serving/``/``resilience/`` is a typo'd or drifted
  name the registry in ``telemetry/metrics.py`` cannot catch.

Every rule is heuristic where it must be (static analysis cannot prove a
buffer is donated); the escape hatch is the suppression grammar in
:mod:`theanompi_tpu.analysis.core` — inline, justified, reported.
"""

from __future__ import annotations

import ast
from typing import Iterator

from theanompi_tpu.analysis.core import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
    Rule,
    SourceFile,
    register,
)

# ---------------------------------------------------------------------------
# ports of the legacy test lints
# ---------------------------------------------------------------------------


@register
class WallClockRule(Rule):
    """``time.time()`` in package code (PR 1's timing lint).

    Durations must come from ``time.perf_counter()`` — ``time.time()`` is
    NTP-steppable and low-resolution.  Wall-clock *stamps* (run ids,
    heartbeat payloads, audit records) mark the line ``lint: wall-ok``
    with the reason wall time is genuinely required.
    """

    name = "wall"
    severity = SEV_ERROR
    description = ("time.time() in timed paths — use time.perf_counter() "
                   "for durations")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "time"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "time"):
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "time.time() — durations use time.perf_counter(); a "
                    "genuine wall-clock stamp marks the line 'lint: "
                    "wall-ok — <why>'")


#: (repo-relative path, enclosing function) pairs exempt from the broad-
#: handler check — the documented correlated-failure teardown sites plus
#: the CLI mains whose whole job is the exit-code contract
SWALLOW_ALLOWLIST = {
    ("theanompi_tpu/parallel/trainer.py", "run"),    # teardown join
    ("theanompi_tpu/parallel/trainer.py", "wait"),   # telemetry finalize
    ("theanompi_tpu/launcher.py", "main"),           # exit-code contract
    ("theanompi_tpu/serving/cli.py", "main"),        # tmserve contract
    ("theanompi_tpu/analysis/cli.py", "main"),       # tmlint contract
    ("theanompi_tpu/fleet/cli.py", "main"),          # tmfleet contract
}

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    return any(isinstance(n, ast.Name) and n.id in _BROAD for n in nodes)


def _stashes_error(handler: ast.ExceptHandler) -> bool:
    """Deferred-delivery pattern: the caught error is assigned somewhere
    (``self._err = e``) for a later re-raise at the consuming site."""
    if not handler.name:
        return False
    for node in ast.walk(handler):
        if isinstance(node, ast.Assign):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == handler.name:
                    return True
    return False


@register
class SwallowRule(Rule):
    """Exception swallowing in package error paths (PR 4's lint).

    The resilience layer only works if failures PROPAGATE: flags bare
    ``except:``, pass-only handler bodies, and broad handlers
    (``Exception``/``BaseException``) that neither re-raise nor stash the
    error for deferred delivery.  The marker counts on the ``except``
    line or the first body line (the PR 4 placement).
    """

    name = "swallow"
    severity = SEV_ERROR
    description = ("bare/pass-only/broad exception handlers swallow "
                   "failures the resilience layer needs")

    def _enclosing_function(self, src: SourceFile,
                            handler: ast.ExceptHandler) -> str:
        for anc in src.ancestors(handler):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc.name
        return "<module>"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            marker_lines = (node.body[0].lineno,) if node.body else ()
            if node.type is None:
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "bare `except:` catches everything, SystemExit "
                    "included", marker_lines)
                continue
            body_is_pass = (len(node.body) == 1
                            and isinstance(node.body[0], ast.Pass))
            if body_is_pass:
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "handler body is only `pass` — the classic swallow",
                    marker_lines)
                continue
            has_raise = any(isinstance(n, ast.Raise) for n in ast.walk(node))
            if (_is_broad(node.type) and not has_raise
                    and not _stashes_error(node)
                    and (src.rel, self._enclosing_function(src, node))
                    not in SWALLOW_ALLOWLIST):
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "broad handler swallows the error (no raise / no "
                    "deferred stash)", marker_lines)


#: files allowed to call np.load (PR 5's lint): checkpoint ``.npz`` bytes
#: must only be read through the verified loader — dataset shards and
#: recorder histories have their own (non-checkpoint) formats.  Serving
#: must NEVER appear here (read-only consumers go through
#: ``load_for_inference``).
NP_LOAD_ALLOWED_PREFIXES = (
    "theanompi_tpu/utils/checkpoint.py",   # THE verified loader
    "theanompi_tpu/utils/recorder.py",     # history .npy snapshots
    "theanompi_tpu/models/data/",          # dataset shard reads
)


@register
class NpLoadRule(Rule):
    """``np.load`` outside the verified-loader allowlist (PR 5's lint).

    A ``np.load(ckpt_path)`` anywhere else bypasses manifest
    verification, the fingerprint check and the recovery chain.
    """

    name = "np-load"
    severity = SEV_ERROR
    description = ("np.load confined to the verified checkpoint loader / "
                   "recorder / dataset allowlist")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.rel.startswith(NP_LOAD_ALLOWED_PREFIXES):
            return
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "load"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("np", "numpy")):
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "np.load outside the verified checkpoint loader "
                    "allowlist — go through theanompi_tpu.utils.checkpoint")


# ---------------------------------------------------------------------------
# donated-buffer escape (the PR 5 async-writer race class)
# ---------------------------------------------------------------------------

_ESCAPE_CALL_ATTRS = {"put", "put_nowait", "submit"}


def _is_np_asarray(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "asarray"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("np", "numpy"))


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return name == "Thread"


@register
class DonatedEscapeRule(Rule):
    """``np.asarray`` view escaping a return/thread/queue boundary.

    ``np.asarray`` on a jax array is zero-copy on the CPU backend: the
    numpy view aliases the device buffer, and if that buffer is later
    donated (``donate_argnums``) the next step rewrites the bytes under
    whoever kept the view — PR 5's torn-.npz race, rediscovered by CRC.
    Flags an ``np.asarray(...)`` whose result (directly or via a local
    name) is returned/yielded, handed to ``queue.put``/``executor.submit``
    / a ``Thread``, stored on ``self`` or into a container — unless a
    ``.copy()`` breaks the aliasing anywhere along the way.
    """

    name = "donated-escape"
    severity = SEV_ERROR
    description = ("np.asarray zero-copy view of a (possibly donated) "
                   "device buffer escapes without .copy()")

    def _sanitized(self, src: SourceFile, node: ast.AST) -> bool:
        """A `.copy()` call wraps ``node`` somewhere up the expression."""
        for anc in src.ancestors(node):
            if (isinstance(anc, ast.Call)
                    and isinstance(anc.func, ast.Attribute)
                    and anc.func.attr == "copy"):
                return True
            if isinstance(anc, ast.stmt):
                return False
        return False

    def _escape_reason(self, src: SourceFile, node: ast.AST) -> str | None:
        """Why ``node``'s value leaves the function, or None.

        Walks up through container displays (a tuple/list/dict keeps the
        view alive verbatim) but stops at an ordinary call — a function
        consuming the view (``np.percentile(arr)``, ``device_put(x)``)
        returns derived data, not the alias.  Queue/executor/thread calls
        are the exception: they hand the object itself across a thread
        boundary, which is exactly the PR 5 race shape.
        """
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.Return, ast.Yield, ast.YieldFrom)):
                return "returned"
            if isinstance(anc, ast.Call):
                if (isinstance(anc.func, ast.Attribute)
                        and anc.func.attr in _ESCAPE_CALL_ATTRS):
                    return f"passed to .{anc.func.attr}()"
                if _is_thread_ctor(anc):
                    return "passed to a Thread"
                return None  # consumed by an ordinary call
            if isinstance(anc, (ast.BinOp, ast.UnaryOp, ast.Compare)):
                return None  # arithmetic/comparison yields derived data
            if isinstance(anc, ast.stmt):
                return None
            # containers, conditionals, attribute/subscript views: the
            # alias survives — keep walking up
        return None

    def _name_sanitized(self, fn: ast.AST, name: str) -> bool:
        """``name.copy()`` appears anywhere in the function (accepts the
        conditional ``a = a.copy()`` ownership-check idiom)."""
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "copy"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == name):
                return True
        return False

    def _name_escapes(self, src: SourceFile, fn: ast.AST, name: str,
                      bound_line: int) -> tuple[int, str] | None:
        """(line, reason) where the bound name leaves the function.

        Loads on lines before the binding are ignored — an early ``return
        x`` guard above a later ``x = np.asarray(x)`` rebinding returns
        the ORIGINAL object, not the view (flow-insensitivity fix).
        """
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name) and node.id == name
                    and isinstance(node.ctx, ast.Load)
                    and node.lineno >= bound_line):
                continue
            reason = self._escape_reason(src, node)
            if reason is not None and not self._sanitized(src, node):
                return node.lineno, reason
            parent = src.parent_map().get(node)
            if isinstance(parent, ast.Assign) and node is parent.value:
                for tgt in parent.targets:
                    if isinstance(tgt, ast.Attribute):
                        return node.lineno, "stored on an attribute"
                    if isinstance(tgt, ast.Subscript):
                        return node.lineno, "stored into a container"
        return None

    def _nearest_function(self, src: SourceFile, node: ast.AST) -> ast.AST | None:
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return anc
        return None

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                continue
            for node in ast.walk(fn):
                if not _is_np_asarray(node):
                    continue
                # nested defs are walked once, in their OWN scope (name
                # tracking below is per-function)
                if self._nearest_function(src, node) is not fn:
                    continue
                if self._sanitized(src, node):
                    continue
                reason = self._escape_reason(src, node)
                if reason is None:
                    # value bound to a simple local name? track the name
                    parent = src.parent_map().get(node)
                    while isinstance(parent, ast.IfExp):
                        parent = src.parent_map().get(parent)
                    if (isinstance(parent, ast.Assign)
                            and len(parent.targets) == 1
                            and isinstance(parent.targets[0], ast.Name)):
                        bound = parent.targets[0].id
                        if not self._name_sanitized(fn, bound):
                            hit = self._name_escapes(src, fn, bound,
                                                     node.lineno)
                            if hit is not None:
                                line, why = hit
                                yield self.finding(
                                    src, node.lineno, node.col_offset,
                                    f"np.asarray view bound to "
                                    f"{bound!r} is {why} at line {line} "
                                    f"without .copy() — a donated buffer "
                                    f"would be rewritten under the reader")
                    continue
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    f"np.asarray view {reason} without .copy() — a "
                    f"donated buffer would be rewritten under the reader")


# ---------------------------------------------------------------------------
# host-sync inside telemetry spans
# ---------------------------------------------------------------------------


def _is_span_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "span")


def _span_in_expr(expr: ast.AST) -> bool:
    """Does this with-item expression produce a telemetry span?  Handles
    the repo's ``with (tel.span(...) if tel else nullcontext()):`` idiom."""
    return any(_is_span_call(n) for n in ast.walk(expr))


@register
class HostSyncRule(Rule):
    """Device sync inside a telemetry span (the timed-path bug class).

    ``float()``/``bool()``/``np.asarray()``/``.item()`` on a device value
    blocks on the device INSIDE the span, so the span times the stall it
    created (PR 2 hoisted exactly these out of the step path).  A span
    that deliberately closes over materialized results — the documented
    "measure execution, not dispatch" pattern — marks the line
    ``lint: host-sync-ok — <why>``.
    """

    name = "host-sync"
    severity = SEV_WARNING
    description = ("float()/bool()/np.asarray/.item() inside a telemetry "
                   "span forces a device sync into the timed region")

    def _span_bound_names(self, fn: ast.AST) -> set[str]:
        """Local names assigned a span (``span = tel.span(...)``)."""
        names: set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _span_in_expr(node.value)):
                names.add(node.targets[0].id)
        return names

    def _sync_calls(self, body: list[ast.stmt]) -> Iterator[ast.Call]:
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if (isinstance(f, ast.Name) and f.id in ("float", "bool")
                        and len(node.args) == 1
                        and not isinstance(node.args[0], ast.Constant)):
                    yield node
                elif _is_np_asarray(node):
                    yield node
                elif isinstance(f, ast.Attribute) and f.attr == "item":
                    yield node

    def _enclosing_span_names(self, src: SourceFile,
                              node: ast.AST) -> set[str]:
        """Span-bound local names visible at ``node`` (its enclosing
        function's assignments, or the module's for top-level code)."""
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self._span_bound_names(anc)
        return self._span_bound_names(src.tree)

    def check(self, src: SourceFile) -> Iterator[Finding]:
        seen: set[int] = set()
        for with_node in ast.walk(src.tree):
            if not isinstance(with_node, (ast.With, ast.AsyncWith)):
                continue
            spanned = any(
                _span_in_expr(item.context_expr)
                or (isinstance(item.context_expr, ast.Name)
                    and item.context_expr.id
                    in self._enclosing_span_names(src, with_node))
                for item in with_node.items)
            if not spanned:
                continue
            for call in self._sync_calls(with_node.body):
                if id(call) in seen:
                    continue
                seen.add(id(call))
                yield self.finding(
                    src, call.lineno, call.col_offset,
                    "host sync inside a telemetry span — the span times "
                    "the stall it causes; hoist the pull past the span, "
                    "or mark 'lint: host-sync-ok — <why>' if the span "
                    "deliberately measures execution")


# ---------------------------------------------------------------------------
# untracked nondeterminism under jit / in the fault plan
# ---------------------------------------------------------------------------

#: files whose WHOLE body must stay deterministic (the PR 4 fault plan:
#: `site:action@index[@attempt]` replays bit-exactly across restarts)
DETERMINISTIC_FILES = (
    "theanompi_tpu/resilience/faults.py",
)

_NONDET_TIME = {"time", "time_ns"}
_NONDET_DATETIME = {"now", "today", "utcnow"}
#: np.random module-level entry points that are fine — seeded constructors
_NP_RANDOM_OK = {"RandomState", "default_rng", "Generator", "SeedSequence",
                 "PCG64", "Philox"}


def _jit_marked(expr: ast.AST) -> bool:
    """Does this expression mention a ``jit`` callable (jax.jit, jit,
    partial(jax.jit, ...))?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return True
        if isinstance(node, ast.Name) and node.id == "jit":
            return True
    return False


@register
class JitNondetRule(Rule):
    """Nondeterminism burned into a jitted trace or the fault plan.

    Inside a function that gets jitted, ``time.time()``, global
    ``np.random.*`` and ``datetime.now()`` run at TRACE time: the value
    becomes a compile-time constant that silently changes on every
    recompile.  In :mod:`theanompi_tpu.resilience.faults` the same calls
    break the deterministic-replay contract outright.
    """

    name = "jit-nondet"
    severity = SEV_ERROR
    description = ("wall clock / global RNG in jitted or fault-plan-"
                   "deterministic code")

    def _jitted_functions(self, src: SourceFile) -> list[ast.AST]:
        """FunctionDefs that are jit-decorated, or whose name is passed
        to a ``jit(...)`` call anywhere in the file (covers the
        ``self._fn = jax.jit(self._impl, ...)`` idiom)."""
        defs: dict[str, list[ast.AST]] = {}
        jitted: list[ast.AST] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                if any(_jit_marked(d) for d in node.decorator_list):
                    jitted.append(node)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and _jit_marked(node.func)):
                continue
            for arg in node.args[:1]:
                name = (arg.id if isinstance(arg, ast.Name)
                        else arg.attr if isinstance(arg, ast.Attribute)
                        else None)
                if name:
                    jitted.extend(defs.get(name, ()))
        return jitted

    def _nondet_calls(self, scope: ast.AST, has_bare_random: bool,
                      ) -> Iterator[tuple[ast.Call, str]]:
        for node in ast.walk(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            f = node.func
            v = f.value
            if (isinstance(v, ast.Name) and v.id == "time"
                    and f.attr in _NONDET_TIME):
                yield node, f"time.{f.attr}()"
            elif (f.attr in _NONDET_DATETIME
                  and isinstance(v, ast.Name) and v.id == "datetime"):
                yield node, f"datetime.{f.attr}()"
            elif (f.attr in _NONDET_DATETIME
                  and isinstance(v, ast.Attribute) and v.attr == "datetime"):
                yield node, f"datetime.datetime.{f.attr}()"
            elif (isinstance(v, ast.Attribute) and v.attr == "random"
                  and isinstance(v.value, ast.Name)
                  and v.value.id in ("np", "numpy")):
                if f.attr not in _NP_RANDOM_OK:
                    yield node, f"np.random.{f.attr}()"
                elif not node.args and not node.keywords:
                    yield node, f"np.random.{f.attr}() with no seed"
            elif (has_bare_random and isinstance(v, ast.Name)
                  and v.id == "random" and f.attr != "seed"):
                yield node, f"random.{f.attr}()"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        has_bare_random = any(
            isinstance(n, ast.Import)
            and any(a.name == "random" for a in n.names)
            for n in ast.walk(src.tree))
        scopes: list[tuple[ast.AST, str]] = []
        if src.rel in DETERMINISTIC_FILES:
            scopes.append((src.tree, "the deterministic fault plan"))
        else:
            scopes.extend((fn, f"jitted function {fn.name!r}")
                          for fn in self._jitted_functions(src))
        seen: set[int] = set()
        for scope, where in scopes:
            for call, what in self._nondet_calls(scope, has_bare_random):
                if id(call) in seen:
                    continue
                seen.add(id(call))
                yield self.finding(
                    src, call.lineno, call.col_offset,
                    f"{what} inside {where} — the value is nondeterministic"
                    f" (trace-time constant under jit); thread it in as an"
                    f" argument instead")


# ---------------------------------------------------------------------------
# exit-code literals
# ---------------------------------------------------------------------------

#: the codes the contract in resilience/codes.py owns (EXIT_CLEAN=0 and
#: argparse's 2 are universal; flagging them would drown the rule in noise)
EXIT_CODE_LITERALS = {70, 75, 76, 77, 78, 79}
EXIT_CODES_SOURCE = "theanompi_tpu/resilience/codes.py"

_EXIT_CALL_NAMES = {"exit", "SystemExit", "_exit"}


@register
class ExitCodeRule(Rule):
    """Bare exit-code literals outside ``resilience/codes.py``.

    A literal ``77`` in a ``sys.exit``/``SystemExit``/comparison is a
    drifted duplicate of the contract waiting to happen (PR 4 created
    ``codes.py`` precisely because two halves of the resilience layer
    must agree).  Import the named constant instead.
    """

    name = "exit-code"
    severity = SEV_ERROR
    description = ("bare 70/75/76/77/78/79 exit-code literal — import from "
                   "theanompi_tpu.resilience.codes")

    def _literals_in(self, node: ast.AST) -> Iterator[ast.Constant]:
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Constant)
                    and type(sub.value) is int
                    and sub.value in EXIT_CODE_LITERALS):
                yield sub

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.rel == EXIT_CODES_SOURCE:
            return
        flagged: set[int] = set()

        def emit(const: ast.Constant, ctx: str):
            if id(const) in flagged:
                return
            flagged.add(id(const))
            yield self.finding(
                src, const.lineno, const.col_offset,
                f"bare exit-code literal {const.value} in {ctx} — use the "
                f"named constant from theanompi_tpu.resilience.codes")

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                f = node.func
                name = (f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else "")
                if name in _EXIT_CALL_NAMES:
                    for arg in node.args:
                        for const in self._literals_in(arg):
                            yield from emit(const, f"{name}()")
            elif isinstance(node, ast.Compare):
                for side in (node.left, *node.comparators):
                    for const in self._literals_in(side):
                        yield from emit(const, "a comparison")


# ---------------------------------------------------------------------------
# data-plane determinism
# ---------------------------------------------------------------------------

#: the tree whose batch content must be a pure function of
#: (seed, epoch, position) — ISSUE 10's cursor-exact resume contract
DATA_PLANE_PREFIX = "theanompi_tpu/models/data/"


@register
class DataDeterminismRule(Rule):
    """Unseeded randomness anywhere in the data plane.

    Mid-epoch resume fast-forwards by cursor arithmetic instead of
    replaying consumed batches, which is only sound if every batch is
    recomputable in isolation from ``(seed, epoch, position)``.  One draw
    from the global numpy RNG (or an unseeded ``RandomState()``) makes
    batch content depend on call order and process history — state a
    checkpoint cannot capture, so the resumed run silently diverges.
    Derive per-call seeds with ``models.data.base.derive_seed`` and feed
    them to a local ``np.random.RandomState``.
    """

    name = "data-determinism"
    severity = SEV_ERROR
    description = ("unseeded np.random.* / global RNG under models/data/ "
                   "breaks cursor-exact mid-epoch resume")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not src.rel.startswith(DATA_PLANE_PREFIX):
            return
        has_bare_random = any(
            isinstance(n, ast.Import)
            and any(a.name == "random" for a in n.names)
            for n in ast.walk(src.tree))
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            f = node.func
            v = f.value
            what = None
            if (isinstance(v, ast.Attribute) and v.attr == "random"
                    and isinstance(v.value, ast.Name)
                    and v.value.id in ("np", "numpy")):
                if f.attr not in _NP_RANDOM_OK:
                    what = f"np.random.{f.attr}()"
                elif not node.args and not node.keywords:
                    what = f"np.random.{f.attr}() with no seed"
            elif (has_bare_random and isinstance(v, ast.Name)
                  and v.id == "random"):
                # random.seed() is flagged too: mutating the global RNG in
                # the data plane is the order-dependence this rule exists
                # to catch, not an exemption from it.
                what = f"random.{f.attr}()"
            if what is not None:
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    f"{what} in the data plane — batch content must be a "
                    f"pure function of (seed, epoch, position) or mid-epoch "
                    f"resume diverges; use np.random.RandomState("
                    f"derive_seed(...)) instead")


# ---------------------------------------------------------------------------
# telemetry event-name registration
# ---------------------------------------------------------------------------

#: trees whose emission sites must use the registered-name tuples from
#: ``telemetry/metrics.py`` (ISSUE 13): the health detectors, tmhealth and
#: the fleet aggregator all key on event names, so a typo'd literal at an
#: emission site silently drops the event from every consumer.  Training
#: code is exempt for now — its names predate the registry.
REGISTERED_NAME_PREFIXES = (
    "theanompi_tpu/serving/",
    "theanompi_tpu/resilience/",
)

#: emission entry points whose FIRST positional argument is an event name
#: (``Telemetry.span/instant/emit_span/observe/gauge/count`` plus the
#: ``self._emit(name, **fields)`` wrappers in the scheduler and sentinel)
_EMIT_NAME_ATTRS = {"span", "instant", "emit_span", "observe", "gauge",
                    "count", "_emit"}


@register
class TelemetryRegisteredNamesRule(Rule):
    """String-literal event names at serving/resilience emission sites.

    The name registry (``SERVE_SPANS``/``RESILIENCE_INSTANTS``/... in
    :mod:`theanompi_tpu.telemetry.metrics`) exists so the emitting site,
    the health detectors, tmhealth and the fleet aggregator all agree on
    one spelling.  A string literal at the call site bypasses it: the
    event still writes, nothing consumes it, and nothing fails loudly.
    Bind the registered tuple to a module constant and pass that.
    """

    name = "telemetry-registered-names"
    severity = SEV_ERROR
    description = ("string-literal telemetry event name in serving/ or "
                   "resilience/ — use the registered names from "
                   "telemetry/metrics.py")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not src.rel.startswith(REGISTERED_NAME_PREFIXES):
            return
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMIT_NAME_ATTRS
                    and node.args):
                continue
            first = node.args[0]
            literal = (isinstance(first, ast.Constant)
                       and type(first.value) is str)
            if not (literal or isinstance(first, ast.JoinedStr)):
                continue
            shown = (repr(first.value) if literal
                     else "an f-string")
            yield self.finding(
                src, first.lineno, first.col_offset,
                f"event name {shown} passed as a literal to "
                f".{node.func.attr}() — bind the registered name from "
                f"theanompi_tpu.telemetry.metrics so detectors and "
                f"aggregators see the same spelling")
