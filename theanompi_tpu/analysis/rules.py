"""tmlint rules: the bug classes this repo has actually hit.

Three rules are straight ports of the PR 1/4/5 test lints (``wall``,
``swallow``, ``np-load``); four are new, distilled from the repo's own
incident history:

- ``donated-escape`` — PR 5's latent async-writer race: ``np.asarray`` on
  a jax array is ZERO-COPY on the CPU backend, so a view that crosses a
  return/thread/queue boundary aliases a buffer the next donated step
  will rewrite underneath the reader (torn .npz, flaky CRC).
- ``host-sync`` — PR 2's hoisting lesson: ``float()``/``bool()``/
  ``np.asarray``/``.item()`` on device values inside a telemetry span
  forces a device sync inside the timed region, so the span measures the
  sync it caused.
- ``jit-nondet`` — wall clocks and global RNG inside a jitted function
  burn a trace-time constant into the executable (different on every
  recompile, invisible at runtime); in the fault plan they break the
  PR 4 determinism contract outright.
- ``exit-code`` — PR 4's exit-code drift: bare 70/75/76/77/78/79 literals
  outside ``resilience/codes.py`` re-create the duplicated contract that
  module exists to kill.
- ``data-determinism`` — ISSUE 10's resume contract: one unseeded
  ``np.random.*`` draw in ``models/data/`` makes batch content depend on
  call order, which a mid-epoch cursor fast-forward cannot reproduce.
- ``telemetry-registered-names`` — ISSUE 13's health detectors and the
  fleet aggregator key on event names; a string-literal name at an
  emission site in ``serving/``/``resilience/`` is a typo'd or drifted
  name the registry in ``telemetry/metrics.py`` cannot catch.

The concurrency tier (ISSUE 15) — every threading bug shipped so far
(PR 5's torn async snapshot, PR 10's ``on_supervisor`` registration
race) was found by accident; these make thread discipline a checked
invariant:

- ``atomic-publish`` — durable artifacts (JSON reports, manifests,
  health files) must publish via tmp→``os.replace``; a direct or
  append-mode write is a torn read waiting for a crash, unless the
  format provably tolerates torn tails (JSONL sinks — suppress with
  that justification).
- ``guarded-state`` — in a class that owns a ``Lock``/``RLock``, an
  attribute assigned both under ``with self._lock:`` and outside it is
  the PR 10 registration-race shape: half the writers think the lock
  protects it.
- ``thread-lifecycle`` — every ``threading.Thread`` carries a ``name``
  (tmhealth/blackbox dumps and py-spy output must identify the seam);
  non-daemon threads need a reachable ``join`` or they outlive the run.
- ``lock-order`` — nested ``with``-acquisitions are checked against the
  declared :data:`LOCK_ORDER_DAG` (``layers.LAYER_DAG`` style); an
  undeclared nesting is a deadlock candidate.

Every rule is heuristic where it must be (static analysis cannot prove a
buffer is donated); the escape hatch is the suppression grammar in
:mod:`theanompi_tpu.analysis.core` — inline, justified, reported.
"""

from __future__ import annotations

import ast
from typing import Iterator

from theanompi_tpu.analysis.core import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
    Rule,
    SourceFile,
    register,
)

# ---------------------------------------------------------------------------
# ports of the legacy test lints
# ---------------------------------------------------------------------------


@register
class WallClockRule(Rule):
    """``time.time()`` in package code (PR 1's timing lint).

    Durations must come from ``time.perf_counter()`` — ``time.time()`` is
    NTP-steppable and low-resolution.  Wall-clock *stamps* (run ids,
    heartbeat payloads, audit records) mark the line ``lint: wall-ok``
    with the reason wall time is genuinely required.
    """

    name = "wall"
    severity = SEV_ERROR
    description = ("time.time() in timed paths — use time.perf_counter() "
                   "for durations")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "time"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "time"):
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "time.time() — durations use time.perf_counter(); a "
                    "genuine wall-clock stamp marks the line 'lint: "
                    "wall-ok — <why>'")


#: (repo-relative path, enclosing function) pairs exempt from the broad-
#: handler check — the documented correlated-failure teardown sites plus
#: the CLI mains whose whole job is the exit-code contract
SWALLOW_ALLOWLIST = {
    ("theanompi_tpu/parallel/trainer.py", "run"),    # teardown join
    ("theanompi_tpu/parallel/trainer.py", "wait"),   # telemetry finalize
    ("theanompi_tpu/launcher.py", "main"),           # exit-code contract
    ("theanompi_tpu/serving/cli.py", "main"),        # tmserve contract
    ("theanompi_tpu/analysis/cli.py", "main"),       # tmlint contract
    ("theanompi_tpu/fleet/cli.py", "main"),          # tmfleet contract
    ("theanompi_tpu/router/cli.py", "main"),         # tmrouter contract
}

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    return any(isinstance(n, ast.Name) and n.id in _BROAD for n in nodes)


def _stashes_error(handler: ast.ExceptHandler) -> bool:
    """Deferred-delivery pattern: the caught error is assigned somewhere
    (``self._err = e``) for a later re-raise at the consuming site."""
    if not handler.name:
        return False
    for node in ast.walk(handler):
        if isinstance(node, ast.Assign):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == handler.name:
                    return True
    return False


@register
class SwallowRule(Rule):
    """Exception swallowing in package error paths (PR 4's lint).

    The resilience layer only works if failures PROPAGATE: flags bare
    ``except:``, pass-only handler bodies, and broad handlers
    (``Exception``/``BaseException``) that neither re-raise nor stash the
    error for deferred delivery.  The marker counts on the ``except``
    line or the first body line (the PR 4 placement).
    """

    name = "swallow"
    severity = SEV_ERROR
    description = ("bare/pass-only/broad exception handlers swallow "
                   "failures the resilience layer needs")

    def _enclosing_function(self, src: SourceFile,
                            handler: ast.ExceptHandler) -> str:
        for anc in src.ancestors(handler):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc.name
        return "<module>"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            marker_lines = (node.body[0].lineno,) if node.body else ()
            if node.type is None:
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "bare `except:` catches everything, SystemExit "
                    "included", marker_lines)
                continue
            body_is_pass = (len(node.body) == 1
                            and isinstance(node.body[0], ast.Pass))
            if body_is_pass:
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "handler body is only `pass` — the classic swallow",
                    marker_lines)
                continue
            has_raise = any(isinstance(n, ast.Raise) for n in ast.walk(node))
            if (_is_broad(node.type) and not has_raise
                    and not _stashes_error(node)
                    and (src.rel, self._enclosing_function(src, node))
                    not in SWALLOW_ALLOWLIST):
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "broad handler swallows the error (no raise / no "
                    "deferred stash)", marker_lines)


#: files allowed to call np.load (PR 5's lint): checkpoint ``.npz`` bytes
#: must only be read through the verified loader — dataset shards and
#: recorder histories have their own (non-checkpoint) formats.  Serving
#: must NEVER appear here (read-only consumers go through
#: ``load_for_inference``).
NP_LOAD_ALLOWED_PREFIXES = (
    "theanompi_tpu/utils/checkpoint.py",   # THE verified loader
    "theanompi_tpu/utils/recorder.py",     # history .npy snapshots
    "theanompi_tpu/models/data/",          # dataset shard reads
)


@register
class NpLoadRule(Rule):
    """``np.load`` outside the verified-loader allowlist (PR 5's lint).

    A ``np.load(ckpt_path)`` anywhere else bypasses manifest
    verification, the fingerprint check and the recovery chain.
    """

    name = "np-load"
    severity = SEV_ERROR
    description = ("np.load confined to the verified checkpoint loader / "
                   "recorder / dataset allowlist")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.rel.startswith(NP_LOAD_ALLOWED_PREFIXES):
            return
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "load"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("np", "numpy")):
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "np.load outside the verified checkpoint loader "
                    "allowlist — go through theanompi_tpu.utils.checkpoint")


# ---------------------------------------------------------------------------
# donated-buffer escape (the PR 5 async-writer race class)
# ---------------------------------------------------------------------------

_ESCAPE_CALL_ATTRS = {"put", "put_nowait", "submit"}


def _is_np_asarray(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "asarray"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("np", "numpy"))


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return name == "Thread"


@register
class DonatedEscapeRule(Rule):
    """``np.asarray`` view escaping a return/thread/queue boundary.

    ``np.asarray`` on a jax array is zero-copy on the CPU backend: the
    numpy view aliases the device buffer, and if that buffer is later
    donated (``donate_argnums``) the next step rewrites the bytes under
    whoever kept the view — PR 5's torn-.npz race, rediscovered by CRC.
    Flags an ``np.asarray(...)`` whose result (directly or via a local
    name) is returned/yielded, handed to ``queue.put``/``executor.submit``
    / a ``Thread``, stored on ``self`` or into a container — unless a
    ``.copy()`` breaks the aliasing anywhere along the way.
    """

    name = "donated-escape"
    severity = SEV_ERROR
    description = ("np.asarray zero-copy view of a (possibly donated) "
                   "device buffer escapes without .copy()")

    def _sanitized(self, src: SourceFile, node: ast.AST) -> bool:
        """A `.copy()` call wraps ``node`` somewhere up the expression."""
        for anc in src.ancestors(node):
            if (isinstance(anc, ast.Call)
                    and isinstance(anc.func, ast.Attribute)
                    and anc.func.attr == "copy"):
                return True
            if isinstance(anc, ast.stmt):
                return False
        return False

    def _escape_reason(self, src: SourceFile, node: ast.AST) -> str | None:
        """Why ``node``'s value leaves the function, or None.

        Walks up through container displays (a tuple/list/dict keeps the
        view alive verbatim) but stops at an ordinary call — a function
        consuming the view (``np.percentile(arr)``, ``device_put(x)``)
        returns derived data, not the alias.  Queue/executor/thread calls
        are the exception: they hand the object itself across a thread
        boundary, which is exactly the PR 5 race shape.
        """
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.Return, ast.Yield, ast.YieldFrom)):
                return "returned"
            if isinstance(anc, ast.Call):
                if (isinstance(anc.func, ast.Attribute)
                        and anc.func.attr in _ESCAPE_CALL_ATTRS):
                    return f"passed to .{anc.func.attr}()"
                if _is_thread_ctor(anc):
                    return "passed to a Thread"
                return None  # consumed by an ordinary call
            if isinstance(anc, (ast.BinOp, ast.UnaryOp, ast.Compare)):
                return None  # arithmetic/comparison yields derived data
            if isinstance(anc, ast.stmt):
                return None
            # containers, conditionals, attribute/subscript views: the
            # alias survives — keep walking up
        return None

    def _name_sanitized(self, fn: ast.AST, name: str) -> bool:
        """``name.copy()`` appears anywhere in the function (accepts the
        conditional ``a = a.copy()`` ownership-check idiom)."""
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "copy"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == name):
                return True
        return False

    def _name_escapes(self, src: SourceFile, fn: ast.AST, name: str,
                      bound_line: int) -> tuple[int, str] | None:
        """(line, reason) where the bound name leaves the function.

        Loads on lines before the binding are ignored — an early ``return
        x`` guard above a later ``x = np.asarray(x)`` rebinding returns
        the ORIGINAL object, not the view (flow-insensitivity fix).
        """
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name) and node.id == name
                    and isinstance(node.ctx, ast.Load)
                    and node.lineno >= bound_line):
                continue
            reason = self._escape_reason(src, node)
            if reason is not None and not self._sanitized(src, node):
                return node.lineno, reason
            parent = src.parent_map().get(node)
            if isinstance(parent, ast.Assign) and node is parent.value:
                for tgt in parent.targets:
                    if isinstance(tgt, ast.Attribute):
                        return node.lineno, "stored on an attribute"
                    if isinstance(tgt, ast.Subscript):
                        return node.lineno, "stored into a container"
        return None

    def _nearest_function(self, src: SourceFile, node: ast.AST) -> ast.AST | None:
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return anc
        return None

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                continue
            for node in ast.walk(fn):
                if not _is_np_asarray(node):
                    continue
                # nested defs are walked once, in their OWN scope (name
                # tracking below is per-function)
                if self._nearest_function(src, node) is not fn:
                    continue
                if self._sanitized(src, node):
                    continue
                reason = self._escape_reason(src, node)
                if reason is None:
                    # value bound to a simple local name? track the name
                    parent = src.parent_map().get(node)
                    while isinstance(parent, ast.IfExp):
                        parent = src.parent_map().get(parent)
                    if (isinstance(parent, ast.Assign)
                            and len(parent.targets) == 1
                            and isinstance(parent.targets[0], ast.Name)):
                        bound = parent.targets[0].id
                        if not self._name_sanitized(fn, bound):
                            hit = self._name_escapes(src, fn, bound,
                                                     node.lineno)
                            if hit is not None:
                                line, why = hit
                                yield self.finding(
                                    src, node.lineno, node.col_offset,
                                    f"np.asarray view bound to "
                                    f"{bound!r} is {why} at line {line} "
                                    f"without .copy() — a donated buffer "
                                    f"would be rewritten under the reader")
                    continue
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    f"np.asarray view {reason} without .copy() — a "
                    f"donated buffer would be rewritten under the reader")


# ---------------------------------------------------------------------------
# host-sync inside telemetry spans
# ---------------------------------------------------------------------------


def _is_span_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "span")


def _span_in_expr(expr: ast.AST) -> bool:
    """Does this with-item expression produce a telemetry span?  Handles
    the repo's ``with (tel.span(...) if tel else nullcontext()):`` idiom."""
    return any(_is_span_call(n) for n in ast.walk(expr))


@register
class HostSyncRule(Rule):
    """Device sync inside a telemetry span (the timed-path bug class).

    ``float()``/``bool()``/``np.asarray()``/``.item()`` on a device value
    blocks on the device INSIDE the span, so the span times the stall it
    created (PR 2 hoisted exactly these out of the step path).  A span
    that deliberately closes over materialized results — the documented
    "measure execution, not dispatch" pattern — marks the line
    ``lint: host-sync-ok — <why>``.
    """

    name = "host-sync"
    severity = SEV_WARNING
    description = ("float()/bool()/np.asarray/.item() inside a telemetry "
                   "span forces a device sync into the timed region")

    def _span_bound_names(self, fn: ast.AST) -> set[str]:
        """Local names assigned a span (``span = tel.span(...)``)."""
        names: set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _span_in_expr(node.value)):
                names.add(node.targets[0].id)
        return names

    def _sync_calls(self, body: list[ast.stmt]) -> Iterator[ast.Call]:
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if (isinstance(f, ast.Name) and f.id in ("float", "bool")
                        and len(node.args) == 1
                        and not isinstance(node.args[0], ast.Constant)):
                    yield node
                elif _is_np_asarray(node):
                    yield node
                elif isinstance(f, ast.Attribute) and f.attr == "item":
                    yield node

    def _enclosing_span_names(self, src: SourceFile,
                              node: ast.AST) -> set[str]:
        """Span-bound local names visible at ``node`` (its enclosing
        function's assignments, or the module's for top-level code)."""
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self._span_bound_names(anc)
        return self._span_bound_names(src.tree)

    def check(self, src: SourceFile) -> Iterator[Finding]:
        seen: set[int] = set()
        for with_node in ast.walk(src.tree):
            if not isinstance(with_node, (ast.With, ast.AsyncWith)):
                continue
            spanned = any(
                _span_in_expr(item.context_expr)
                or (isinstance(item.context_expr, ast.Name)
                    and item.context_expr.id
                    in self._enclosing_span_names(src, with_node))
                for item in with_node.items)
            if not spanned:
                continue
            for call in self._sync_calls(with_node.body):
                if id(call) in seen:
                    continue
                seen.add(id(call))
                yield self.finding(
                    src, call.lineno, call.col_offset,
                    "host sync inside a telemetry span — the span times "
                    "the stall it causes; hoist the pull past the span, "
                    "or mark 'lint: host-sync-ok — <why>' if the span "
                    "deliberately measures execution")


# ---------------------------------------------------------------------------
# untracked nondeterminism under jit / in the fault plan
# ---------------------------------------------------------------------------

#: files whose WHOLE body must stay deterministic (the PR 4 fault plan:
#: `site:action@index[@attempt]` replays bit-exactly across restarts)
DETERMINISTIC_FILES = (
    "theanompi_tpu/resilience/faults.py",
)

_NONDET_TIME = {"time", "time_ns"}
_NONDET_DATETIME = {"now", "today", "utcnow"}
#: np.random module-level entry points that are fine — seeded constructors
_NP_RANDOM_OK = {"RandomState", "default_rng", "Generator", "SeedSequence",
                 "PCG64", "Philox"}


def _jit_marked(expr: ast.AST) -> bool:
    """Does this expression mention a ``jit`` callable (jax.jit, jit,
    partial(jax.jit, ...))?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return True
        if isinstance(node, ast.Name) and node.id == "jit":
            return True
    return False


@register
class JitNondetRule(Rule):
    """Nondeterminism burned into a jitted trace or the fault plan.

    Inside a function that gets jitted, ``time.time()``, global
    ``np.random.*`` and ``datetime.now()`` run at TRACE time: the value
    becomes a compile-time constant that silently changes on every
    recompile.  In :mod:`theanompi_tpu.resilience.faults` the same calls
    break the deterministic-replay contract outright.
    """

    name = "jit-nondet"
    severity = SEV_ERROR
    description = ("wall clock / global RNG in jitted or fault-plan-"
                   "deterministic code")

    def _jitted_functions(self, src: SourceFile) -> list[ast.AST]:
        """FunctionDefs that are jit-decorated, or whose name is passed
        to a ``jit(...)`` call anywhere in the file (covers the
        ``self._fn = jax.jit(self._impl, ...)`` idiom)."""
        defs: dict[str, list[ast.AST]] = {}
        jitted: list[ast.AST] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                if any(_jit_marked(d) for d in node.decorator_list):
                    jitted.append(node)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and _jit_marked(node.func)):
                continue
            for arg in node.args[:1]:
                name = (arg.id if isinstance(arg, ast.Name)
                        else arg.attr if isinstance(arg, ast.Attribute)
                        else None)
                if name:
                    jitted.extend(defs.get(name, ()))
        return jitted

    def _nondet_calls(self, scope: ast.AST, has_bare_random: bool,
                      ) -> Iterator[tuple[ast.Call, str]]:
        for node in ast.walk(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            f = node.func
            v = f.value
            if (isinstance(v, ast.Name) and v.id == "time"
                    and f.attr in _NONDET_TIME):
                yield node, f"time.{f.attr}()"
            elif (f.attr in _NONDET_DATETIME
                  and isinstance(v, ast.Name) and v.id == "datetime"):
                yield node, f"datetime.{f.attr}()"
            elif (f.attr in _NONDET_DATETIME
                  and isinstance(v, ast.Attribute) and v.attr == "datetime"):
                yield node, f"datetime.datetime.{f.attr}()"
            elif (isinstance(v, ast.Attribute) and v.attr == "random"
                  and isinstance(v.value, ast.Name)
                  and v.value.id in ("np", "numpy")):
                if f.attr not in _NP_RANDOM_OK:
                    yield node, f"np.random.{f.attr}()"
                elif not node.args and not node.keywords:
                    yield node, f"np.random.{f.attr}() with no seed"
            elif (has_bare_random and isinstance(v, ast.Name)
                  and v.id == "random" and f.attr != "seed"):
                yield node, f"random.{f.attr}()"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        has_bare_random = any(
            isinstance(n, ast.Import)
            and any(a.name == "random" for a in n.names)
            for n in ast.walk(src.tree))
        scopes: list[tuple[ast.AST, str]] = []
        if src.rel in DETERMINISTIC_FILES:
            scopes.append((src.tree, "the deterministic fault plan"))
        else:
            scopes.extend((fn, f"jitted function {fn.name!r}")
                          for fn in self._jitted_functions(src))
        seen: set[int] = set()
        for scope, where in scopes:
            for call, what in self._nondet_calls(scope, has_bare_random):
                if id(call) in seen:
                    continue
                seen.add(id(call))
                yield self.finding(
                    src, call.lineno, call.col_offset,
                    f"{what} inside {where} — the value is nondeterministic"
                    f" (trace-time constant under jit); thread it in as an"
                    f" argument instead")


# ---------------------------------------------------------------------------
# exit-code literals
# ---------------------------------------------------------------------------

#: the codes the contract in resilience/codes.py owns (EXIT_CLEAN=0 and
#: argparse's 2 are universal; flagging them would drown the rule in noise)
EXIT_CODE_LITERALS = {70, 75, 76, 77, 78, 79}
EXIT_CODES_SOURCE = "theanompi_tpu/resilience/codes.py"

_EXIT_CALL_NAMES = {"exit", "SystemExit", "_exit"}


@register
class ExitCodeRule(Rule):
    """Bare exit-code literals outside ``resilience/codes.py``.

    A literal ``77`` in a ``sys.exit``/``SystemExit``/comparison is a
    drifted duplicate of the contract waiting to happen (PR 4 created
    ``codes.py`` precisely because two halves of the resilience layer
    must agree).  Import the named constant instead.
    """

    name = "exit-code"
    severity = SEV_ERROR
    description = ("bare 70/75/76/77/78/79 exit-code literal — import from "
                   "theanompi_tpu.resilience.codes")

    def _literals_in(self, node: ast.AST) -> Iterator[ast.Constant]:
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Constant)
                    and type(sub.value) is int
                    and sub.value in EXIT_CODE_LITERALS):
                yield sub

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.rel == EXIT_CODES_SOURCE:
            return
        flagged: set[int] = set()

        def emit(const: ast.Constant, ctx: str):
            if id(const) in flagged:
                return
            flagged.add(id(const))
            yield self.finding(
                src, const.lineno, const.col_offset,
                f"bare exit-code literal {const.value} in {ctx} — use the "
                f"named constant from theanompi_tpu.resilience.codes")

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                f = node.func
                name = (f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else "")
                if name in _EXIT_CALL_NAMES:
                    for arg in node.args:
                        for const in self._literals_in(arg):
                            yield from emit(const, f"{name}()")
            elif isinstance(node, ast.Compare):
                for side in (node.left, *node.comparators):
                    for const in self._literals_in(side):
                        yield from emit(const, "a comparison")


# ---------------------------------------------------------------------------
# data-plane determinism
# ---------------------------------------------------------------------------

#: the tree whose batch content must be a pure function of
#: (seed, epoch, position) — ISSUE 10's cursor-exact resume contract
DATA_PLANE_PREFIX = "theanompi_tpu/models/data/"


@register
class DataDeterminismRule(Rule):
    """Unseeded randomness anywhere in the data plane.

    Mid-epoch resume fast-forwards by cursor arithmetic instead of
    replaying consumed batches, which is only sound if every batch is
    recomputable in isolation from ``(seed, epoch, position)``.  One draw
    from the global numpy RNG (or an unseeded ``RandomState()``) makes
    batch content depend on call order and process history — state a
    checkpoint cannot capture, so the resumed run silently diverges.
    Derive per-call seeds with ``models.data.base.derive_seed`` and feed
    them to a local ``np.random.RandomState``.
    """

    name = "data-determinism"
    severity = SEV_ERROR
    description = ("unseeded np.random.* / global RNG under models/data/ "
                   "breaks cursor-exact mid-epoch resume")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not src.rel.startswith(DATA_PLANE_PREFIX):
            return
        has_bare_random = any(
            isinstance(n, ast.Import)
            and any(a.name == "random" for a in n.names)
            for n in ast.walk(src.tree))
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            f = node.func
            v = f.value
            what = None
            if (isinstance(v, ast.Attribute) and v.attr == "random"
                    and isinstance(v.value, ast.Name)
                    and v.value.id in ("np", "numpy")):
                if f.attr not in _NP_RANDOM_OK:
                    what = f"np.random.{f.attr}()"
                elif not node.args and not node.keywords:
                    what = f"np.random.{f.attr}() with no seed"
            elif (has_bare_random and isinstance(v, ast.Name)
                  and v.id == "random"):
                # random.seed() is flagged too: mutating the global RNG in
                # the data plane is the order-dependence this rule exists
                # to catch, not an exemption from it.
                what = f"random.{f.attr}()"
            if what is not None:
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    f"{what} in the data plane — batch content must be a "
                    f"pure function of (seed, epoch, position) or mid-epoch "
                    f"resume diverges; use np.random.RandomState("
                    f"derive_seed(...)) instead")


# ---------------------------------------------------------------------------
# telemetry event-name registration
# ---------------------------------------------------------------------------

#: trees whose emission sites must use the registered-name tuples from
#: ``telemetry/metrics.py`` (ISSUE 13): the health detectors, tmhealth and
#: the fleet aggregator all key on event names, so a typo'd literal at an
#: emission site silently drops the event from every consumer.  Training
#: code is exempt for now — its names predate the registry.
REGISTERED_NAME_PREFIXES = (
    "theanompi_tpu/serving/",
    "theanompi_tpu/resilience/",
    # ISSUE 19: the router's dispatch/redistribute/scale decisions feed
    # the same consumers — its router.* names are registered too
    "theanompi_tpu/router/",
    # ISSUE 16: the attribution/ledger emitters live by the same contract
    # (their attr.*/prof.*/ledger.* names are registered in metrics.py)
    "theanompi_tpu/telemetry/profile.py",
    "theanompi_tpu/telemetry/ledger.py",
    "theanompi_tpu/telemetry/prof.py",
    # ISSUE 20: the async rules' per-round instants feed the
    # async_staleness detector — their easgd.*/gosgd.*/exchange.* names
    # bind from metrics.py (ASYNC_INSTANTS/ASYNC_GAUGES/EXCHANGE_COUNTS)
    "theanompi_tpu/parallel/easgd.py",
    "theanompi_tpu/parallel/gosgd.py",
)

#: emission entry points whose FIRST positional argument is an event name
#: (``Telemetry.span/instant/emit_span/observe/gauge/count`` plus the
#: ``self._emit(name, **fields)`` wrappers in the scheduler and sentinel)
_EMIT_NAME_ATTRS = {"span", "instant", "emit_span", "observe", "gauge",
                    "count", "_emit"}


@register
class TelemetryRegisteredNamesRule(Rule):
    """String-literal event names at serving/resilience emission sites.

    The name registry (``SERVE_SPANS``/``RESILIENCE_INSTANTS``/... in
    :mod:`theanompi_tpu.telemetry.metrics`) exists so the emitting site,
    the health detectors, tmhealth and the fleet aggregator all agree on
    one spelling.  A string literal at the call site bypasses it: the
    event still writes, nothing consumes it, and nothing fails loudly.
    Bind the registered tuple to a module constant and pass that.
    """

    name = "telemetry-registered-names"
    severity = SEV_ERROR
    description = ("string-literal telemetry event name in serving/ or "
                   "resilience/ — use the registered names from "
                   "telemetry/metrics.py")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not src.rel.startswith(REGISTERED_NAME_PREFIXES):
            return
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMIT_NAME_ATTRS
                    and node.args):
                continue
            first = node.args[0]
            literal = (isinstance(first, ast.Constant)
                       and type(first.value) is str)
            if not (literal or isinstance(first, ast.JoinedStr)):
                continue
            shown = (repr(first.value) if literal
                     else "an f-string")
            yield self.finding(
                src, first.lineno, first.col_offset,
                f"event name {shown} passed as a literal to "
                f".{node.func.attr}() — bind the registered name from "
                f"theanompi_tpu.telemetry.metrics so detectors and "
                f"aggregators see the same spelling")


# ---------------------------------------------------------------------------
# the concurrency tier (ISSUE 15)
# ---------------------------------------------------------------------------


def _nearest_function(src: SourceFile, node: ast.AST) -> ast.AST | None:
    """The innermost enclosing function scope, or None at module level."""
    for anc in src.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return anc
    return None


@register
class AtomicPublishRule(Rule):
    """Durable artifacts publish tmp→``os.replace`` — never directly.

    A reader (resume, tmhealth, the fleet aggregator, a human) that
    opens a half-written JSON file sees garbage; a crash between
    truncate and flush *loses the previous good artifact too*.  The
    proven idiom everywhere else in this repo (checkpoint manifests,
    HEALTH.json, flight-recorder dumps, the lint report itself) is
    write-to-``<path>.tmp`` then ``os.replace`` — crash-atomic on POSIX.

    Heuristics, per function scope: a write-mode ``open()`` whose path
    expression mentions ``.tmp`` (directly or via a name assigned in
    the same function) is the idiom's first half and must be paired
    with an ``os.replace`` in the same function; any other ``"w"``/
    ``"x"`` open is a direct write; ``"a"`` opens are torn-tail-prone
    appends.  Streams that provably tolerate torn tails (JSONL event
    sinks, append-only audit logs — their readers skip unparseable
    final lines) suppress with that justification:
    ``# lint: atomic-publish-ok — <why torn reads are safe>``.
    """

    name = "atomic-publish"
    severity = SEV_ERROR
    description = ("durable-file write outside the tmp→os.replace idiom — "
                   "fix or justify (JSONL torn-tail tolerance)")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        opens: list[tuple[ast.AST | None, ast.Call, str]] = []
        replaced: set[ast.AST | None] = set()
        assigns: dict[tuple[ast.AST | None, str], ast.AST] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                if self._is_open(node):
                    mode = self._mode(node)
                    if mode and mode[0] in "wxa":
                        opens.append(
                            (_nearest_function(src, node), node, mode))
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "replace"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "os"):
                    replaced.add(_nearest_function(src, node))
            elif (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                scope = _nearest_function(src, node)
                assigns[(scope, node.targets[0].id)] = node.value
        for scope, call, mode in opens:
            path = call.args[0] if call.args else None
            if mode[0] == "a":
                yield self.finding(
                    src, call.lineno, call.col_offset,
                    f"append-mode open({mode!r}) to a durable file — a "
                    f"crash mid-write leaves a torn tail; if every reader "
                    f"skips unparseable tails (JSONL), mark the line "
                    f"'lint: atomic-publish-ok — <why>'")
            elif self._tmpish(path, scope, assigns):
                if scope not in replaced:
                    yield self.finding(
                        src, call.lineno, call.col_offset,
                        "tmp file written but never published — pair the "
                        ".tmp write with os.replace in the same function")
            else:
                yield self.finding(
                    src, call.lineno, call.col_offset,
                    f"direct open({mode!r}) write to a durable path — "
                    f"write '<path>.tmp' then os.replace(tmp, path) so a "
                    f"crash never tears the artifact or loses the "
                    f"previous one")

    def _is_open(self, call: ast.Call) -> bool:
        return isinstance(call.func, ast.Name) and call.func.id == "open"

    def _mode(self, call: ast.Call) -> str | None:
        """The mode string when statically known, else None (skipped)."""
        expr = None
        if len(call.args) >= 2:
            expr = call.args[1]
        else:
            for kw in call.keywords:
                if kw.arg == "mode":
                    expr = kw.value
        if expr is None:
            return "r"
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        return None

    def _tmpish(self, path: ast.AST | None, scope: ast.AST | None,
                assigns: dict) -> bool:
        if path is None:
            return False
        for n in ast.walk(path):
            if (isinstance(n, ast.Constant) and isinstance(n.value, str)
                    and ".tmp" in n.value):
                return True
            if isinstance(n, ast.Name):
                bound = assigns.get((scope, n.id))
                if bound is not None and any(
                        isinstance(m, ast.Constant)
                        and isinstance(m.value, str) and ".tmp" in m.value
                        for m in ast.walk(bound)):
                    return True
        return False


@register
class GuardedStateRule(Rule):
    """Attribute assigned both under and outside ``with self._lock:``.

    The PR 10 shape: ``FleetScheduler._sups`` was written by the
    episode thread's callback and read by ``_preempt`` — one side held
    the lock, the other didn't, and a preemption arriving in the gap
    was silently lost.  In a class that owns a ``Lock``/``RLock``, an
    attribute rebound both inside and outside lock-guarded code is that
    bug waiting to recur.

    What counts as guarded: a lexical ``with self.<lock>:`` ancestor,
    or the whole body of a method whose every ``self.m()`` call site in
    the class sits under the lock (the ``EventSink._rotate`` idiom —
    helpers documented 'call with the lock held').  ``__init__`` is
    exempt: construction precedes sharing.
    """

    name = "guarded-state"
    severity = SEV_ERROR
    description = ("attribute assigned both under and outside the owning "
                   "class's lock — the registration-race shape")

    _LOCK_CTORS = ("Lock", "RLock")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_attrs = self._lock_attrs(cls)
            if not lock_attrs:
                continue
            methods = {m.name: m for m in cls.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            locked = self._locked_methods(src, methods, lock_attrs)
            guarded: dict[str, list] = {}
            unguarded: dict[str, list] = {}
            for mname, m in methods.items():
                if mname == "__init__":
                    continue
                for node in ast.walk(m):
                    for attr, line, col in self._self_assigns(node):
                        if attr in lock_attrs:
                            continue
                        bucket = (guarded if mname in locked
                                  or self._under_lock(src, node, m,
                                                      lock_attrs)
                                  else unguarded)
                        bucket.setdefault(attr, []).append((line, col))
            for attr in sorted(set(guarded) & set(unguarded)):
                for line, col in unguarded[attr]:
                    yield self.finding(
                        src, line, col,
                        f"self.{attr} is assigned here without the lock "
                        f"but under 'with self.{sorted(lock_attrs)[0]}:' "
                        f"elsewhere in the class — every writer must "
                        f"agree on whether the lock protects it")

    def _lock_attrs(self, cls: ast.ClassDef) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            f = node.value.func
            ctor = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if ctor not in self._LOCK_CTORS:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    out.add(t.attr)
        return out

    def _self_assigns(self, node: ast.AST):
        targets = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Tuple):
                targets.extend(t.elts)
            elif (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                yield t.attr, t.lineno, t.col_offset

    def _is_lock_expr(self, expr: ast.AST, lock_attrs: set[str]) -> bool:
        return (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in lock_attrs)

    def _under_lock(self, src: SourceFile, node: ast.AST, method: ast.AST,
                    lock_attrs: set[str]) -> bool:
        for anc in src.ancestors(node):
            if anc is method:
                return False
            if isinstance(anc, (ast.With, ast.AsyncWith)) and any(
                    self._is_lock_expr(it.context_expr, lock_attrs)
                    for it in anc.items):
                return True
        return False

    def _locked_methods(self, src: SourceFile, methods: dict,
                        lock_attrs: set[str]) -> set[str]:
        """Methods whose every ``self.m()`` call site runs under the
        lock (directly or from another such method) — their bodies
        count as guarded.  One call site outside the lock disqualifies:
        ambiguity is exactly the bug this rule exists to surface."""
        sites: dict[str, list[bool]] = {}
        for mname, m in methods.items():
            for node in ast.walk(m):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods):
                    under = self._under_lock(src, node, m, lock_attrs)
                    sites.setdefault(node.func.attr, []).append(
                        under or mname)  # True, or the calling method
        locked: set[str] = set()
        changed = True
        while changed:
            changed = False
            for callee, callers in sites.items():
                if callee in locked:
                    continue
                if all(c is True or c in locked for c in callers):
                    locked.add(callee)
                    changed = True
        return locked


@register
class ThreadLifecycleRule(Rule):
    """Every ``threading.Thread`` is named; non-daemon threads join.

    An anonymous thread shows up as ``Thread-3`` in ``tmhealth``
    blackbox dumps, the flight recorder, and py-spy — useless when
    diagnosing exactly the hung-seam incidents those tools exist for.
    And a non-daemon thread nobody joins outlives the run: the process
    can't exit, the supervisor escalates to SIGKILL, and the crash
    looks like a hang.  Daemon threads (all seven seams in this repo)
    need only the name.
    """

    name = "thread-lifecycle"
    severity = SEV_ERROR
    description = ("threading.Thread must carry name=...; non-daemon "
                   "threads need a reachable join()")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        has_join = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "join"
            and not self._path_or_str_join(n.func.value)
            for n in ast.walk(src.tree))
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            kws = {k.arg: k.value for k in node.keywords if k.arg}
            if "name" not in kws:
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "unnamed thread — pass name='<seam>' so health "
                    "dumps, the flight recorder and py-spy can identify "
                    "it")
            d = kws.get("daemon")
            daemon = isinstance(d, ast.Constant) and d.value is True
            if not daemon and not has_join:
                yield self.finding(
                    src, node.lineno, node.col_offset,
                    "non-daemon thread with no join() anywhere in this "
                    "file — it outlives the run and turns clean exits "
                    "into apparent hangs; join it or make it a daemon")

    def _path_or_str_join(self, value: ast.AST) -> bool:
        """``os.path.join`` / ``"sep".join`` are not thread joins."""
        if isinstance(value, ast.Constant):
            return True
        if isinstance(value, ast.Attribute) and value.attr == "path":
            return True
        return False


#: Declared lock-ordering DAG (``layers.LAYER_DAG`` style): innermost
#: locks first, and an entry may only allow inner locks declared EARLIER
#: — so the declaration is acyclic by construction, exactly like the
#: import DAG.  Entry: (name, (file-prefix, lock-attr), allowed-inner,
#: reentrant).  The telemetry leaves allow NOTHING inside them — in
#: particular ``health`` must never acquire ``sink``'s lock: the ticker
#: releases the monitor's lock before emitting (the documented contract
#: in ``telemetry/core.py:_health_tick``).  The fleet scheduler's RLock
#: sits outermost: its passes emit telemetry while holding it, so the
#: sink/flight/health locks may nest inside (that nesting is cross-file
#: and runtime-only; the entry documents it for the day it becomes
#: lexical).
LOCK_ORDER_DAG: tuple = (
    ("sink", ("theanompi_tpu/telemetry/sink.py", "_lock"), (), False),
    ("flight", ("theanompi_tpu/telemetry/flight_recorder.py", "_lock"),
     (), False),
    # ISSUE 16: both leaf locks — the attributor computes under its lock
    # and emits only after release; the ledger's lock guards the
    # append+dedup read-modify-write and never wraps another lock
    ("attrib", ("theanompi_tpu/telemetry/profile.py", "_lock"), (), False),
    ("ledger", ("theanompi_tpu/telemetry/ledger.py", "_lock"), (), False),
    ("health", ("theanompi_tpu/telemetry/health.py", "_lock"), (), False),
    ("watchdog", ("theanompi_tpu/resilience/watchdog.py", "_lock"),
     (), False),
    ("data-hooks", ("theanompi_tpu/models/data/base.py", "_HOOKS_LOCK"),
     (), False),
    ("shm-busy", ("theanompi_tpu/models/data/shm_loader.py", "_busy"),
     (), False),
    ("native-build", ("theanompi_tpu/native/__init__.py", "_build_lock"),
     (), False),
    ("interleave", ("theanompi_tpu/analysis/interleave.py", "_cond"),
     (), False),
    ("scheduler", ("theanompi_tpu/fleet/scheduler.py", "_lock"),
     ("sink", "flight", "health"), True),
)


def validate_lock_order(dag=None) -> None:
    """Reject duplicate names and forward references, like
    ``layers.validate_dag`` — an allowed-inner lock must be declared
    earlier (further inward), which makes cycles unrepresentable."""
    dag = LOCK_ORDER_DAG if dag is None else dag
    seen: list[str] = []
    for name, (prefix, attr), allowed, _reentrant in dag:
        if name in seen:
            raise ValueError(f"lock-order: duplicate lock name {name!r}")
        if not prefix or not attr:
            raise ValueError(f"lock-order: empty prefix/attr on {name!r}")
        for a in allowed:
            if a not in seen:
                raise ValueError(
                    f"lock-order: {name!r} allows {a!r} which is not "
                    f"declared earlier — inner locks must be declared "
                    f"first")
        seen.append(name)


@register
class LockOrderRule(Rule):
    """Nested ``with``-lock acquisitions obey :data:`LOCK_ORDER_DAG`.

    Two threads taking the same two locks in opposite orders is the
    classic deadlock; a declared global order makes it impossible.  The
    check is lexical (same-file nested ``with`` statements, including
    multi-item ``with a, b:`` read left-to-right): acquiring a declared
    lock while holding another is legal only if the held lock's entry
    allows it; re-acquiring a non-reentrant lock is flagged as a
    self-deadlock.  Cross-file nesting (scheduler → telemetry emit) is
    declared in the DAG for documentation but only runtime tools can
    see it — the interleave harness exists for those.
    """

    name = "lock-order"
    severity = SEV_ERROR
    description = ("nested with-lock acquisition not allowed by the "
                   "declared LOCK_ORDER_DAG")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        validate_lock_order()
        decls = [(name, attr, set(allowed), reentrant)
                 for name, (prefix, attr), allowed, reentrant
                 in LOCK_ORDER_DAG if src.rel.startswith(prefix)]
        if not decls:
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = self._held_above(src, node, decls)
            for item in node.items:
                acq = self._declared(item.context_expr, decls)
                if acq is None:
                    continue
                aname, _allowed, _reent = acq
                for hname, hallowed, hreent in held:
                    if hname == aname:
                        if not hreent:
                            yield self.finding(
                                src, item.context_expr.lineno,
                                item.context_expr.col_offset,
                                f"re-acquiring non-reentrant lock "
                                f"{aname!r} while holding it — "
                                f"self-deadlock")
                    elif aname not in hallowed:
                        yield self.finding(
                            src, item.context_expr.lineno,
                            item.context_expr.col_offset,
                            f"acquiring lock {aname!r} while holding "
                            f"{hname!r} — not allowed by LOCK_ORDER_DAG; "
                            f"declare the order or restructure so the "
                            f"locks never nest")
                held.append(acq)

    def _declared(self, expr: ast.AST, decls):
        key = (expr.attr if isinstance(expr, ast.Attribute)
               else expr.id if isinstance(expr, ast.Name) else None)
        for name, attr, allowed, reentrant in decls:
            if key == attr:
                return (name, allowed, reentrant)
        return None

    def _held_above(self, src: SourceFile, node: ast.AST, decls) -> list:
        held = []
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break  # a nested def runs on its caller's schedule,
                # not inside the enclosing with — out of lexical scope
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    acq = self._declared(item.context_expr, decls)
                    if acq is not None:
                        held.append(acq)
        return held
