"""Compiled-artifact auditor: invariants only visible in the HLO.

The AST rules catch what source says; this module catches what XLA
*built*.  Three invariants the repo has already been burned by (or
armored against) are statically checkable on any backend by compiling a
representative step and reading the module text:

- **donation applied** — ``donate_argnums`` is a *request*; a refactor
  that copies a tree before the jit boundary silently doubles HBM and
  no numeric test notices.  Donation that took effect shows up as
  ``input_output_alias`` entries in the module header.
- **collective counts** — the PR 2 lock, generalized: a fused-bucket
  step must compile to O(buckets) all-reduces (not O(leaves)), and
  ``zero1`` must show its reduce-scatter/all-gather pair.  Reuses
  :func:`theanompi_tpu.telemetry.metrics.hlo_collective_counts`.
- **no host callbacks** — a ``pure_callback``/``io_callback`` smuggled
  into a jitted step stalls every step on the host; it compiles to a
  ``custom-call`` with a python-callback target.

One XLA compile per audited program per process (``lru_cache``): the
tier-1 collective-lint shim and the audit tests share the artifacts.
"""

from __future__ import annotations

import functools
import re

from theanompi_tpu.telemetry.metrics import hlo_collective_counts


class HLOAuditError(AssertionError):
    """A compiled artifact violates a locked invariant."""


# -- HLO text parsers --------------------------------------------------------

#: one aliased (donated) parameter entry inside the header's
#: ``input_output_alias={ {0}: (0, {}, may-alias), ... }`` map
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\(")
_ALIAS_MAP_RE = re.compile(r"input_output_alias=\{(.*)")
_CUSTOM_CALL_RE = re.compile(r'custom_call_target="([^"]+)"')

#: custom-call targets that mean "the compiled step re-enters python /
#: the host" — the exact spelling varies by backend and jax version, so
#: match substrings
_CALLBACK_MARKERS = ("callback", "python", "host_compute")


def donation_alias_count(hlo_text: str) -> int:
    """How many parameter buffers the compiled module aliases to outputs
    (donation that actually took effect)."""
    for line in hlo_text.splitlines():
        if "input_output_alias=" not in line:
            continue
        m = _ALIAS_MAP_RE.search(line)
        if m:
            return len(_ALIAS_ENTRY_RE.findall(m.group(1)))
    return 0


def host_callbacks(hlo_text: str) -> list[str]:
    """Python/host custom-call targets appearing in the module."""
    hits = []
    for target in _CUSTOM_CALL_RE.findall(hlo_text):
        low = target.lower()
        if any(mark in low for mark in _CALLBACK_MARKERS):
            hits.append(target)
    return sorted(set(hits))


def audit_text(hlo_text: str) -> dict:
    """Backend-independent facts about one compiled module's text."""
    return {
        "collectives": hlo_collective_counts(hlo_text),
        "alias_count": donation_alias_count(hlo_text),
        "host_callbacks": host_callbacks(hlo_text),
    }


# -- representative train step ----------------------------------------------

#: depth 16 -> 43 param leaves: past the >=30-leaf bar the PR 2
#: acceptance set (bucketing is only provable on a many-leaf model),
#: still tiny enough to compile in seconds on the CPU mesh
TRAIN_MODEL_CFG = {
    "depth": 16, "widen": 1, "batch_size": 2, "image_size": 8,
    "n_train": 32, "n_val": 16, "n_epochs": 1, "precision": "fp32",
    "augment": False, "verbose": False,
}

#: the PR 2 collective-count lock, per audited strategy:
#: op kind -> (min, max) definitions in the compiled step (None = unbounded).
#: psum_bucket: one fused grad bucket + fused metrics pmean + fused state
#: pmean <= 4 all-reduces.  zero1: the scatter/gather pair must exist, and
#: at most 3 all-reduces ride along (grad-clip norm psum + the two fused
#: pmeans).
TRAIN_COLLECTIVE_BUDGETS: dict[str, dict[str, tuple[int, int | None]]] = {
    "psum_bucket": {"all-reduce": (1, 4)},
    "zero1": {"reduce-scatter": (1, None), "all-gather": (1, None),
              "all-reduce": (0, 3)},
    # the leaf-wise baseline the bucket lock is measured AGAINST: one
    # all-reduce per grad leaf, so the floor is the leaf count (asserted
    # dynamically in audit_train_step, not here)
    "psum": {"all-reduce": (1, None)},
}


@functools.lru_cache(maxsize=None)
def _train_artifact(strategy: str, n_data: int = 4) -> dict:
    """Compile the BSP train step for ``strategy``; -> facts + HLO text.

    Cached: one XLA compile per (strategy, mesh) per process, shared by
    the legacy collective-lint shim and the audit tests.
    """
    import jax

    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh
    from theanompi_tpu.utils.helper_funcs import shard_batch
    from theanompi_tpu.utils.recorder import Recorder

    model = WideResNet(dict(TRAIN_MODEL_CFG))
    mesh = make_mesh(n_data=n_data, devices=jax.devices()[:n_data])
    t = BSPTrainer(model, mesh=mesh, exch_strategy=strategy,
                   recorder=Recorder(verbose=False, print_freq=10**9))
    t.compile_iter_fns()
    t.init_state()
    batch = shard_batch(
        mesh,
        next(iter(model.data.train_batches(t.global_batch, 0, seed=0))),
        spec=t.batch_spec)
    text = t.compiled_step_text(batch)
    return {
        "n_param_leaves": len(jax.tree.leaves(t.params)),
        **audit_text(text),
    }


def audit_train_step(strategy: str, n_data: int = 4) -> dict:
    """Audit one exchange strategy's compiled train step.

    -> report dict with ``violations`` (empty = clean) alongside the
    measured facts; raises nothing — callers decide (the CLI raises via
    :func:`run_default_audits`, tests assert on the report).
    """
    facts = _train_artifact(strategy, n_data)
    violations: list[str] = []
    counts = facts["collectives"]
    for op, (lo, hi) in TRAIN_COLLECTIVE_BUDGETS.get(strategy, {}).items():
        n = counts.get(op, 0)
        if n < lo:
            violations.append(
                f"{op}: {n} < locked minimum {lo} (strategy {strategy})")
        if hi is not None and n > hi:
            violations.append(
                f"{op}: {n} > locked maximum {hi} (strategy {strategy}) — "
                f"bucketing regressed to leaf-wise collectives?")
    if strategy == "psum":
        # the baseline must stay leaf-wise, or the bucket lock above is
        # no longer proving anything (XLA started fusing on its own)
        if counts.get("all-reduce", 0) < facts["n_param_leaves"]:
            violations.append(
                f"leaf-wise psum baseline compiled to "
                f"{counts.get('all-reduce', 0)} all-reduces < "
                f"{facts['n_param_leaves']} param leaves — re-evaluate "
                f"the bucket lock")
    # donation: params/state/opt/step are donated leaf-wise; if XLA
    # aliased fewer buffers than the params tree alone has leaves, the
    # donation request silently stopped taking effect
    if facts["alias_count"] < facts["n_param_leaves"]:
        violations.append(
            f"donation not applied: {facts['alias_count']} aliased "
            f"buffers < {facts['n_param_leaves']} param leaves")
    if facts["host_callbacks"]:
        violations.append(
            f"host callbacks in the compiled step: "
            f"{facts['host_callbacks']}")
    return {"kind": "train", "strategy": strategy, "n_data": n_data,
            "ok": not violations, "violations": violations, **facts}


# -- representative serve step ----------------------------------------------

#: tiny TransformerLM (the serving tests' shape) — structure is what the
#: audit reads; no training needed
SERVE_MODEL_CFG = {
    "batch_size": 2, "n_train": 64, "n_val": 32, "seq_len": 32,
    "vocab": 61, "dim": 32, "heads": 2, "n_layers": 2,
    "dropout": 0.0, "n_epochs": 1, "precision": "fp32",
}


@functools.lru_cache(maxsize=None)
def _serve_artifact() -> dict:
    """Compile the fixed-batch decode step; -> facts + metadata."""
    import jax
    import jax.numpy as jnp

    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.serving.engine import InferenceEngine

    model = TransformerLM(dict(SERVE_MODEL_CFG))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    eng = InferenceEngine(model, params, block_size=8, max_batch=2)
    b = eng.max_batch
    args = (
        eng.params, eng._k, eng._v,
        jnp.zeros((b, eng.max_blocks_per_seq), jnp.int32),
        jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.float32),
        jnp.zeros((b,), jnp.int32),
        eng._base_key,
    )
    text = eng._decode_fn.lower(*args).compile().as_text()
    return {"max_batch": b, **audit_text(text)}


def audit_serve_step() -> dict:
    """Audit the serving decode step: k/v pools donated (the paged-cache
    in-place contract), no collectives (single-device serve), no host
    callbacks."""
    facts = _serve_artifact()
    violations: list[str] = []
    if facts["alias_count"] < 2:
        violations.append(
            f"k/v pool donation not applied: {facts['alias_count']} "
            f"aliased buffers < 2 — decode copies the whole cache per "
            f"token")
    if facts["collectives"]:
        violations.append(
            f"collectives in the serve step: {facts['collectives']}")
    if facts["host_callbacks"]:
        violations.append(
            f"host callbacks in the serve step: {facts['host_callbacks']}")
    return {"kind": "serve", "ok": not violations,
            "violations": violations, **facts}


# -- entry point -------------------------------------------------------------

#: what ``tmlint --hlo-audit`` (and the tier-1 test) audits: the two
#: strategies the acceptance criteria name, plus the serve decode step
DEFAULT_TRAIN_STRATEGIES = ("psum_bucket", "zero1")


def run_default_audits(n_data: int = 4) -> list[dict]:
    """Audit the default artifact set; raise :class:`HLOAuditError` on
    any violation (the CLI maps this to exit 1; the completed reports
    ride on the exception's ``reports`` attribute so the CLI can still
    publish the artifact that shows WHAT failed)."""
    import os

    # the device-count fix must land BEFORE the first backend touch —
    # jax.devices() initializes the backend and latches the count, after
    # which force_host_devices is a no-op for this process
    if "--xla_force_host_platform_device_count=" \
            not in os.environ.get("XLA_FLAGS", ""):
        from theanompi_tpu.parallel.mesh import force_host_devices

        force_host_devices(max(n_data, 8))

    import jax

    if len(jax.devices()) < n_data:
        raise HLOAuditError(
            f"need {n_data} devices for the train-step audit, have "
            f"{len(jax.devices())} — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_data} "
            f"before jax initializes")
    reports = [audit_train_step(s, n_data) for s in DEFAULT_TRAIN_STRATEGIES]
    reports.append(audit_serve_step())
    bad = [r for r in reports if not r["ok"]]
    if bad:
        err = HLOAuditError("; ".join(
            f"[{r['kind']}:{r.get('strategy', 'decode')}] {v}"
            for r in bad for v in r["violations"]))
        err.reports = reports
        raise err
    return reports
