"""Compiled-artifact auditor: invariants only visible in the HLO.

The AST rules catch what source says; this module catches what XLA
*built*.  Three invariants the repo has already been burned by (or
armored against) are statically checkable on any backend by compiling a
representative step and reading the module text:

- **donation applied** — ``donate_argnums`` is a *request*; a refactor
  that copies a tree before the jit boundary silently doubles HBM and
  no numeric test notices.  Donation that took effect shows up as
  ``input_output_alias`` entries in the module header.
- **collective counts** — the PR 2 lock, generalized: a fused-bucket
  step must compile to O(buckets) all-reduces (not O(leaves)), and
  ``zero1`` must show its reduce-scatter/all-gather pair.  Reuses
  :func:`theanompi_tpu.telemetry.metrics.hlo_collective_counts`.
- **no host callbacks** — a ``pure_callback``/``io_callback`` smuggled
  into a jitted step stalls every step on the host; it compiles to a
  ``custom-call`` with a python-callback target.

One XLA compile per audited program per process (``lru_cache``): the
tier-1 collective-lint shim and the audit tests share the artifacts.
"""

from __future__ import annotations

import functools
import re

from theanompi_tpu.telemetry.metrics import hlo_collective_counts


class HLOAuditError(AssertionError):
    """A compiled artifact violates a locked invariant."""


# -- HLO text parsers --------------------------------------------------------

#: one aliased (donated) parameter entry inside the header's
#: ``input_output_alias={ {0}: (0, {}, may-alias), ... }`` map
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\(")
_ALIAS_MAP_RE = re.compile(r"input_output_alias=\{(.*)")
_CUSTOM_CALL_RE = re.compile(r'custom_call_target="([^"]+)"')

#: custom-call targets that mean "the compiled step re-enters python /
#: the host" — the exact spelling varies by backend and jax version, so
#: match substrings
_CALLBACK_MARKERS = ("callback", "python", "host_compute")


def donation_alias_count(hlo_text: str) -> int:
    """How many parameter buffers the compiled module aliases to outputs
    (donation that actually took effect)."""
    for line in hlo_text.splitlines():
        if "input_output_alias=" not in line:
            continue
        m = _ALIAS_MAP_RE.search(line)
        if m:
            return len(_ALIAS_ENTRY_RE.findall(m.group(1)))
    return 0


def host_callbacks(hlo_text: str) -> list[str]:
    """Python/host custom-call targets appearing in the module."""
    hits = []
    for target in _CUSTOM_CALL_RE.findall(hlo_text):
        low = target.lower()
        if any(mark in low for mark in _CALLBACK_MARKERS):
            hits.append(target)
    return sorted(set(hits))


def audit_text(hlo_text: str) -> dict:
    """Backend-independent facts about one compiled module's text."""
    return {
        "collectives": hlo_collective_counts(hlo_text),
        "alias_count": donation_alias_count(hlo_text),
        "host_callbacks": host_callbacks(hlo_text),
    }


# -- entry-computation dataflow (the overlap-schedule discriminator) ---------
#
# Text POSITION cannot prove a collective schedule: the CPU scheduler
# already interleaves op definitions positionally even when the collectives
# are mutually independent and free to sink to the end.  What the overlap
# transform actually guarantees — and what survives every optimization
# pass — is DATAFLOW: with ``exch_overlap`` on, bucket k+1's collective
# transitively depends on bucket k's result (the select fence in
# ``parallel/overlap.py``), while the fused schedule's per-bucket
# collectives have no edges between them at all.  So the auditor parses
# the optimized entry computation into an operand graph and counts
# collective->collective reachability.

_ENTRY_OP_RE = re.compile(r"\)?\s*([a-z][\w\-]*)\(")
_ENTRY_OPERAND_RE = re.compile(r"%([\w.\-]+)")

#: op kinds the chain discriminator follows (same spellings as
#: ``telemetry.metrics.COLLECTIVE_OPS`` definitions)
_CHAIN_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather",
                      "collective-permute")


def entry_dependency_graph(hlo_text: str):
    """Parse the ENTRY computation -> ``(graph, order)``.

    ``graph`` maps instruction name -> ``(op_kind, operand_names)``;
    ``order`` is definition order.  Operand extraction is by ``%name``
    reference, which over-approximates (attribute refs like ``to_apply=``
    point at non-entry computations and resolve to nothing) — safe for
    reachability, which only follows names defined in the entry.
    """
    in_entry = False
    graph: dict = {}
    order: list = []
    for ln in hlo_text.splitlines():
        if ln.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            if ln.startswith("}"):
                break
            s = ln.strip()
            if " = " not in s:
                continue
            lhs, rhs = s.split(" = ", 1)
            name = lhs.strip().removeprefix("ROOT ").lstrip("%")
            m = _ENTRY_OP_RE.search(rhs)
            op = m.group(1) if m else "?"
            args = rhs.split("(", 1)[1] if "(" in rhs else ""
            graph[name] = (op, _ENTRY_OPERAND_RE.findall(args))
            order.append(name)
    return graph, order


def collective_chain_stats(hlo_text: str) -> dict:
    """Dataflow facts that discriminate overlapped from fused schedules.

    - ``chained_same_kind``: ordered pairs (A, B) of SAME-KIND collectives
      where B transitively depends on A.  The overlap chain makes this
      >= n_buckets - 1 (transitively n*(n-1)/2 for a full chain); the
      fused schedule's grad collectives are mutually independent, so it
      is 0.  Same-kind only, because zero1's all-gathers inherently
      depend on reduce-scatters (through the update) in EITHER schedule.
    - ``interleaved_pairs``: chained pairs whose downstream collective
      depends on at least one fusion the upstream one does not — i.e.
      backward compute sits ON the chain between the two collectives,
      which is the overlap claim itself (comm k || compute k+1).
    """
    graph, order = entry_dependency_graph(hlo_text)
    colls = [(n, graph[n][0]) for n in order
             if graph[n][0] in _CHAIN_COLLECTIVES]
    # transitive closure in definition order (operands precede uses in
    # printed HLO, so one forward pass resolves every ancestor set; an
    # iterative walk — entry computations run to thousands of ops)
    memo: dict = {}
    for name in order:
        acc: set = set()
        for o in graph[name][1]:
            if o in graph:
                acc.add(o)
                acc |= memo.get(o, set())
        memo[name] = acc

    def ancestors(name):
        return memo.get(name, set())

    chained = 0
    interleaved = 0
    for b, kind_b in colls:
        anc_b = ancestors(b)
        for a, kind_a in colls:
            if a == b or kind_a != kind_b or a not in anc_b:
                continue
            chained += 1
            between = {x for x in anc_b - ancestors(a) - {a}
                       if graph[x][0] in ("fusion", "convolution", "dot")}
            if between:
                interleaved += 1
    return {
        "n_collectives": len(colls),
        "chained_same_kind": chained,
        "interleaved_pairs": interleaved,
    }


# -- representative train step ----------------------------------------------

#: depth 16 -> 43 param leaves: past the >=30-leaf bar the PR 2
#: acceptance set (bucketing is only provable on a many-leaf model),
#: still tiny enough to compile in seconds on the CPU mesh
TRAIN_MODEL_CFG = {
    "depth": 16, "widen": 1, "batch_size": 2, "image_size": 8,
    "n_train": 32, "n_val": 16, "n_epochs": 1, "precision": "fp32",
    "augment": False, "verbose": False,
}

#: the PR 2 collective-count lock, per audited strategy:
#: op kind -> (min, max) definitions in the compiled step (None = unbounded).
#: psum_bucket: one fused grad bucket + fused metrics pmean + fused state
#: pmean <= 4 all-reduces.  zero1: the scatter/gather pair must exist, and
#: at most 3 all-reduces ride along (grad-clip norm psum + the two fused
#: pmeans).
TRAIN_COLLECTIVE_BUDGETS: dict[str, dict[str, tuple[int, int | None]]] = {
    "psum_bucket": {"all-reduce": (1, 4)},
    "zero1": {"reduce-scatter": (1, None), "all-gather": (1, None),
              "all-reduce": (0, 3)},
    # the leaf-wise baseline the bucket lock is measured AGAINST: one
    # all-reduce per grad leaf, so the floor is the leaf count (asserted
    # dynamically in audit_train_step, not here)
    "psum": {"all-reduce": (1, None)},
}


@functools.lru_cache(maxsize=None)
def _train_artifact(strategy: str, n_data: int = 4, overlap: bool = False,
                    bucket_mb: float | None = None) -> dict:
    """Compile the BSP train step for ``strategy``; -> facts + HLO text.

    Cached: one XLA compile per (strategy, mesh, overlap, bucket size)
    per process, shared by the legacy collective-lint shim and the audit
    tests.  ``bucket_mb`` shrinks the fused-bucket cap (the overlap audit
    needs >= 2 grad buckets out of this tiny model; the default 4 MiB
    packs everything into one).
    """
    import jax

    from theanompi_tpu.models.wide_resnet import WideResNet
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh
    from theanompi_tpu.utils.helper_funcs import shard_batch
    from theanompi_tpu.utils.recorder import Recorder

    model = WideResNet(dict(TRAIN_MODEL_CFG))
    mesh = make_mesh(n_data=n_data, devices=jax.devices()[:n_data])
    kw = {} if bucket_mb is None else {"exch_bucket_mb": bucket_mb}
    t = BSPTrainer(model, mesh=mesh, exch_strategy=strategy,
                   exch_overlap=overlap,
                   recorder=Recorder(verbose=False, print_freq=10**9), **kw)
    t.compile_iter_fns()
    t.init_state()
    batch = shard_batch(
        mesh,
        next(iter(model.data.train_batches(t.global_batch, 0, seed=0))),
        spec=t.batch_spec)
    text = t.compiled_step_text(batch)
    buckets = t.exchanger.bucket_summary(
        t._shard_param_structs(), t._exchange_axis_size())
    return {
        "n_param_leaves": len(jax.tree.leaves(t.params)),
        "n_buckets": None if buckets is None else buckets["n_buckets"],
        "chain": collective_chain_stats(text),
        **audit_text(text),
    }


def audit_train_step(strategy: str, n_data: int = 4) -> dict:
    """Audit one exchange strategy's compiled train step.

    -> report dict with ``violations`` (empty = clean) alongside the
    measured facts; raises nothing — callers decide (the CLI raises via
    :func:`run_default_audits`, tests assert on the report).
    """
    facts = _train_artifact(strategy, n_data)
    violations: list[str] = []
    counts = facts["collectives"]
    for op, (lo, hi) in TRAIN_COLLECTIVE_BUDGETS.get(strategy, {}).items():
        n = counts.get(op, 0)
        if n < lo:
            violations.append(
                f"{op}: {n} < locked minimum {lo} (strategy {strategy})")
        if hi is not None and n > hi:
            violations.append(
                f"{op}: {n} > locked maximum {hi} (strategy {strategy}) — "
                f"bucketing regressed to leaf-wise collectives?")
    if strategy == "psum":
        # the baseline must stay leaf-wise, or the bucket lock above is
        # no longer proving anything (XLA started fusing on its own)
        if counts.get("all-reduce", 0) < facts["n_param_leaves"]:
            violations.append(
                f"leaf-wise psum baseline compiled to "
                f"{counts.get('all-reduce', 0)} all-reduces < "
                f"{facts['n_param_leaves']} param leaves — re-evaluate "
                f"the bucket lock")
    # donation: params/state/opt/step are donated leaf-wise; if XLA
    # aliased fewer buffers than the params tree alone has leaves, the
    # donation request silently stopped taking effect
    if facts["alias_count"] < facts["n_param_leaves"]:
        violations.append(
            f"donation not applied: {facts['alias_count']} aliased "
            f"buffers < {facts['n_param_leaves']} param leaves")
    if facts["host_callbacks"]:
        violations.append(
            f"host callbacks in the compiled step: "
            f"{facts['host_callbacks']}")
    return {"kind": "train", "strategy": strategy, "n_data": n_data,
            "ok": not violations, "violations": violations, **facts}


# -- overlapped-exchange schedule audit (ISSUE 12) ---------------------------

#: bucket cap for the overlap artifacts — small enough that the depth-16
#: WRN's fp32 grads split into several buckets (the chain needs >= 2)
OVERLAP_AUDIT_BUCKET_MB = 0.125

#: strategies the default overlap audit locks (the all-reduce family and
#: the scatter/gather family — one representative of each chained shape)
DEFAULT_OVERLAP_STRATEGIES = ("psum_bucket", "zero1")


def audit_overlap_schedule(strategy: str, n_data: int = 2) -> dict:
    """Prove the ``exch_overlap`` schedule in the optimized HLO.

    Compiles the step twice at :data:`OVERLAP_AUDIT_BUCKET_MB` — fused
    and overlapped — and checks, on the operand graph:

    - the overlapped module carries a same-kind collective dependency
      chain of >= n_buckets - 1 edges, and the chain passes through
      backward fusions (``interleaved_pairs``) — collectives issue
      *during* backward, not after it;
    - the fused module still audits as trailing (ZERO same-kind edges) —
      the negative proof that the discriminator measures the transform,
      not scheduler noise;
    - overlap changes the SCHEDULE only: per-kind collective counts are
      identical to the fused module, and donation is intact.
    """
    fused = _train_artifact(strategy, n_data,
                            bucket_mb=OVERLAP_AUDIT_BUCKET_MB)
    over = _train_artifact(strategy, n_data, overlap=True,
                           bucket_mb=OVERLAP_AUDIT_BUCKET_MB)
    violations: list[str] = []
    n_buckets = over["n_buckets"] or 0
    if n_buckets < 2:
        violations.append(
            f"overlap artifact packed only {n_buckets} grad bucket(s) at "
            f"{OVERLAP_AUDIT_BUCKET_MB} MiB — nothing to chain; shrink "
            f"OVERLAP_AUDIT_BUCKET_MB")
    need = max(1, n_buckets - 1)
    if over["chain"]["chained_same_kind"] < need:
        violations.append(
            f"overlap ON but only {over['chain']['chained_same_kind']} "
            f"collective chain edges < {need} (buckets={n_buckets}) — the "
            f"fence chain was optimized away; collectives can sink behind "
            f"backward again")
    if over["chain"]["interleaved_pairs"] < need:
        violations.append(
            f"overlap chain exists but only "
            f"{over['chain']['interleaved_pairs']} chained pairs run "
            f"through backward fusions < {need} — comm is chained but not "
            f"interleaved with compute")
    if fused["chain"]["chained_same_kind"] != 0:
        violations.append(
            f"fused baseline shows {fused['chain']['chained_same_kind']} "
            f"same-kind collective chain edges (expected 0: trailing / "
            f"unconstrained) — the discriminator no longer isolates the "
            f"overlap transform")
    if over["collectives"] != fused["collectives"]:
        violations.append(
            f"overlap changed collective counts: {over['collectives']} != "
            f"fused {fused['collectives']} — the fence must reorder, never "
            f"add or split collectives")
    if over["alias_count"] < over["n_param_leaves"]:
        violations.append(
            f"donation not applied under overlap: {over['alias_count']} "
            f"aliased buffers < {over['n_param_leaves']} param leaves")
    if over["host_callbacks"]:
        violations.append(
            f"host callbacks in the overlapped step: "
            f"{over['host_callbacks']}")
    return {"kind": "train-overlap", "strategy": strategy, "n_data": n_data,
            "n_buckets": n_buckets, "ok": not violations,
            "violations": violations,
            "chain": over["chain"], "fused_chain": fused["chain"],
            "collectives": over["collectives"],
            "alias_count": over["alias_count"]}


# -- representative serve step ----------------------------------------------

#: tiny TransformerLM (the serving tests' shape) — structure is what the
#: audit reads; no training needed
SERVE_MODEL_CFG = {
    "batch_size": 2, "n_train": 64, "n_val": 32, "seq_len": 32,
    "vocab": 61, "dim": 32, "heads": 2, "n_layers": 2,
    "dropout": 0.0, "n_epochs": 1, "precision": "fp32",
}


@functools.lru_cache(maxsize=None)
def _serve_artifact() -> dict:
    """Compile the fixed-batch decode step; -> facts + metadata."""
    import jax
    import jax.numpy as jnp

    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.serving.engine import InferenceEngine

    model = TransformerLM(dict(SERVE_MODEL_CFG))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    eng = InferenceEngine(model, params, block_size=8, max_batch=2)
    b = eng.max_batch
    args = (
        eng.params, eng._k, eng._v,
        jnp.zeros((b, eng.max_blocks_per_seq), jnp.int32),
        jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.float32),
        jnp.zeros((b,), jnp.int32),
        eng._base_key,
    )
    text = eng._decode_fn.lower(*args).compile().as_text()
    return {"max_batch": b, **audit_text(text)}


def audit_serve_step() -> dict:
    """Audit the serving decode step: k/v pools donated (the paged-cache
    in-place contract), no collectives (single-device serve), no host
    callbacks."""
    facts = _serve_artifact()
    violations: list[str] = []
    if facts["alias_count"] < 2:
        violations.append(
            f"k/v pool donation not applied: {facts['alias_count']} "
            f"aliased buffers < 2 — decode copies the whole cache per "
            f"token")
    if facts["collectives"]:
        violations.append(
            f"collectives in the serve step: {facts['collectives']}")
    if facts["host_callbacks"]:
        violations.append(
            f"host callbacks in the serve step: {facts['host_callbacks']}")
    return {"kind": "serve", "ok": not violations,
            "violations": violations, **facts}


@functools.lru_cache(maxsize=None)
def _serve_prefill_artifact() -> dict:
    """Compile one partial-prefill (suffix) program — the prefix-cache
    hit path (ISSUE 17), one-block suffix bucket; -> facts + metadata."""
    import jax
    import jax.numpy as jnp

    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.serving.engine import InferenceEngine

    model = TransformerLM(dict(SERVE_MODEL_CFG))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    eng = InferenceEngine(model, params, block_size=8, max_batch=2)
    s_pad = eng.block_size  # smallest suffix bucket: one block
    fn = jax.jit(eng._prefill_suffix_impl, donate_argnums=(1, 2))
    args = (
        eng.params, eng._k, eng._v,
        jnp.zeros((eng.max_blocks_per_seq,), jnp.int32),
        jnp.zeros((s_pad // eng.block_size,), jnp.int32),
        jnp.zeros((s_pad,), jnp.int32),
        jnp.asarray(eng.block_size, jnp.int32),
        jnp.asarray(eng.block_size + 1, jnp.int32),
        jnp.asarray(0.0, jnp.float32),
        jnp.asarray(0, jnp.int32),
        eng._base_key,
    )
    text = fn.lower(*args).compile().as_text()
    return {"s_pad": s_pad, **audit_text(text)}


def audit_serve_prefill() -> dict:
    """Audit the prefix-cache partial-prefill step (ISSUE 17): same
    contract as decode — k/v pools donated (a cache hit must not copy the
    pools to append suffix K/V), no collectives, no host callbacks."""
    facts = _serve_prefill_artifact()
    violations: list[str] = []
    if facts["alias_count"] < 2:
        violations.append(
            f"k/v pool donation not applied in partial prefill: "
            f"{facts['alias_count']} aliased buffers < 2 — every "
            f"prefix-cache hit copies the whole cache")
    if facts["collectives"]:
        violations.append(
            f"collectives in the partial-prefill step: "
            f"{facts['collectives']}")
    if facts["host_callbacks"]:
        violations.append(
            f"host callbacks in the partial-prefill step: "
            f"{facts['host_callbacks']}")
    return {"kind": "serve-prefill", "ok": not violations,
            "violations": violations, **facts}


# -- serve decode kernel dispatch (ISSUE 18) ---------------------------------

#: TPU-legal shape for the kernel-dispatch lowering: the paged-decode
#: gate needs ``head_dim % 128 == 0`` and ``heads % 8 == 0`` (fp32), and
#: the fused int8 matmul needs 128-divisible bands — dim 1024 over 8
#: heads at the default 1024-element quant chunk is the smallest config
#: satisfying both.  This model is only TRACED and LOWERED (never
#: compiled or run), so the big dims cost trace time, not compile time.
SERVE_KERNEL_CFG = {**SERVE_MODEL_CFG, "dim": 1024, "heads": 8}


@functools.lru_cache(maxsize=None)
def _serve_decode_kernel_artifact() -> dict:
    """Gather the decode-kernel dispatch facts (ISSUE 18).

    Three artifact families, all produced on whatever host backend runs
    the audit (CPU in CI):

    - **TPU lowerings** of the full decode step at :data:`SERVE_KERNEL_CFG`
      with the kernel pinned on vs off.  ``decode_impl`` is a static
      cache field, so pinning ``"kernel"`` lowers the COMPILED pallas
      call even on a CPU host (``lowering_platforms=("tpu",)``) — the
      positive proof is ``tpu_custom_call`` per layer, the negative proof
      is zero custom calls in the ``"off"`` lowering.
    - a **direct int8 lowering** of :func:`~theanompi_tpu.ops.quant.
      int8_matmul` over an actual quantized engine weight leaf (the
      engine-level lowering above keeps int8 in interpret mode off-TPU,
      so the custom call is proven at the kernel boundary).
    - a **CPU-compiled kernel-on step** at :data:`SERVE_MODEL_CFG` plus a
      bit-parity run: the kernel variant must keep the pool-donation /
      zero-collective contract of :func:`audit_serve_step`, and
      ``interpret=True`` must match the fallback BIT-for-bit over
      crafted tables covering null-block padding, a prefix-shared block
      and ragged (non-block-multiple) positions.
    """
    import jax
    import jax.numpy as jnp

    from theanompi_tpu.models.transformer_lm import TransformerLM
    from theanompi_tpu.ops.quant import (
        QuantizedTensor,
        int8_matmul,
        int8_matmul_supported,
        quantize_chunked,
    )
    from theanompi_tpu.serving.engine import InferenceEngine
    from theanompi_tpu.serving.kv_cache import PagedKVCache

    facts: dict = {"n_layers": SERVE_KERNEL_CFG["n_layers"]}

    # -- TPU lowerings: kernel pinned on vs off --------------------------
    model = TransformerLM(dict(SERVE_KERNEL_CFG))
    params, _ = model.init_params(jax.random.PRNGKey(0))
    int8_leaf = None
    for variant in ("on", "off"):
        eng = InferenceEngine(model, params, block_size=8, max_batch=2,
                              quantize_int8=True, decode_kernel=variant)
        if variant == "on":
            # pin the COMPILED kernel (off-TPU "on" resolves to the
            # interpreter); static aux, so the TPU lowering is exactly
            # what a TPU host would build
            eng.decode_impl = "kernel"
            int8_leaf = next(
                w for w in jax.tree.leaves(
                    eng.params,
                    is_leaf=lambda x: isinstance(x, QuantizedTensor))
                if isinstance(w, QuantizedTensor)
                and int8_matmul_supported(w.shape, int(w.q.shape[1]),
                                          compiled=True))
        b = eng.max_batch
        args = (
            eng.params, eng._k, eng._v,
            jnp.zeros((b, eng.max_blocks_per_seq), jnp.int32),
            jnp.zeros((b,), jnp.int32),
            jnp.zeros((b,), jnp.int32),
            jnp.zeros((b,), jnp.float32),
            jnp.zeros((b,), jnp.int32),
            eng._base_key,
        )
        text = jax.jit(eng._decode_impl, donate_argnums=(1, 2)) \
            .trace(*args).lower(lowering_platforms=("tpu",)).as_text()
        facts[f"custom_calls_{variant}"] = text.count("tpu_custom_call")

    # -- direct int8 kernel lowering over a real engine weight -----------
    x = jnp.zeros((8, int(int8_leaf.shape[0])), jnp.float32)
    text = jax.jit(lambda xx, ww: int8_matmul(xx, ww, interpret=False)) \
        .trace(x, int8_leaf).lower(lowering_platforms=("tpu",)).as_text()
    facts["custom_calls_int8"] = text.count("tpu_custom_call")

    # -- CPU-compiled kernel-on step: donation contract survives ---------
    model_s = TransformerLM(dict(SERVE_MODEL_CFG))
    params_s, _ = model_s.init_params(jax.random.PRNGKey(0))
    eng_s = InferenceEngine(model_s, params_s, block_size=8, max_batch=2,
                            decode_kernel="on")
    b = eng_s.max_batch
    args = (
        eng_s.params, eng_s._k, eng_s._v,
        jnp.zeros((b, eng_s.max_blocks_per_seq), jnp.int32),
        jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.float32),
        jnp.zeros((b,), jnp.int32),
        eng_s._base_key,
    )
    text = eng_s._decode_fn.lower(*args).compile().as_text()
    facts.update(audit_text(text))

    # -- bit-parity: kernel (interpret) vs fallback ----------------------
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(0x18), 4)
    bs, h, d, nblocks = 4, 2, 16, 6
    kpool = jax.random.normal(k1, (1, nblocks, bs, h, d), jnp.float32)
    vpool = jax.random.normal(k2, (1, nblocks, bs, h, d), jnp.float32)
    # slot 0 spans blocks (1, 2) with a mid-block position; slot 1 SHARES
    # prefix block 1 (the refcounted copy-on-write case) and pads with
    # null blocks
    tables = jnp.asarray([[1, 2, 0], [1, 3, 0]], jnp.int32)
    positions = jnp.asarray([6, 2], jnp.int32)
    q = jax.random.normal(k3, (2, h, d), jnp.float32)
    outs = {}
    for impl in ("kernel_interpret", "fallback"):
        cache = PagedKVCache(kpool, vpool, tables, bs, decode_impl=impl)
        outs[impl] = cache.attend_decode(0, q, positions)
    facts["decode_parity_bitwise"] = bool(
        (outs["kernel_interpret"] == outs["fallback"]).all())

    # -- int8 kernel vs dequantize-then-matmul tolerance -----------------
    w = jax.random.normal(k4, (64, 24), jnp.float32)
    qq, ss = quantize_chunked(w, jax.random.PRNGKey(7), 24)
    qt = QuantizedTensor(qq, ss, (64, 24), jnp.dtype(jnp.float32))
    xs = jax.random.normal(jax.random.PRNGKey(8), (3, 64), jnp.float32)
    got = int8_matmul(xs, qt, interpret=True)
    ref = xs @ qt.dequantize()
    denom = float(jnp.max(jnp.abs(ref))) or 1.0
    facts["int8_rel_err"] = float(jnp.max(jnp.abs(got - ref))) / denom
    return facts


#: int8 kernel vs dequantize-then-matmul: same int8 payload, so only the
#: scale-application association differs — normal fp32 rounding, ~1e-7
INT8_REL_TOL = 1e-5


def audit_serve_decode_kernel() -> dict:
    """Audit the serving decode fast path (ISSUE 18): the pallas paged
    decode kernel and fused int8 matmul actually dispatch as TPU custom
    calls (with the kernel-off lowering as the negative proof), the
    kernel-on step keeps the donation / zero-collective contract, and
    the kernel is bit-identical to the fallback on CPU."""
    facts = _serve_decode_kernel_artifact()
    violations: list[str] = []
    if facts["custom_calls_on"] < facts["n_layers"]:
        violations.append(
            f"kernel-on TPU lowering has {facts['custom_calls_on']} "
            f"tpu_custom_call(s) < n_layers={facts['n_layers']} — the "
            f"paged decode kernel is not dispatching per layer")
    if facts["custom_calls_off"] != 0:
        violations.append(
            f"kernel-off TPU lowering has {facts['custom_calls_off']} "
            f"tpu_custom_call(s) — the negative proof failed, so the "
            f"positive count above proves nothing")
    if facts["custom_calls_int8"] < 1:
        violations.append(
            "int8_matmul TPU lowering has no tpu_custom_call — the "
            "fused int8 kernel is not compiling to a Mosaic call")
    if facts["alias_count"] < 2:
        violations.append(
            f"k/v pool donation not applied in the kernel-on step: "
            f"{facts['alias_count']} aliased buffers < 2")
    if facts["collectives"]:
        violations.append(
            f"collectives in the kernel-on serve step: "
            f"{facts['collectives']}")
    if facts["host_callbacks"]:
        violations.append(
            f"host callbacks in the kernel-on serve step: "
            f"{facts['host_callbacks']}")
    if not facts["decode_parity_bitwise"]:
        violations.append(
            "pallas paged decode (interpret) is NOT bit-identical to the "
            "fallback across null blocks / shared prefix / ragged "
            "positions")
    if facts["int8_rel_err"] > INT8_REL_TOL:
        violations.append(
            f"int8 kernel deviates from dequantize-then-matmul: rel err "
            f"{facts['int8_rel_err']:.2e} > {INT8_REL_TOL:.0e}")
    return {"kind": "serve-kernel", "ok": not violations,
            "violations": violations, **facts}


# -- entry point -------------------------------------------------------------

#: what ``tmlint --hlo-audit`` (and the tier-1 test) audits: the two
#: strategies the acceptance criteria name, their overlapped-schedule
#: locks (ISSUE 12 — the BASELINE step-7 gate), plus the serve decode,
#: partial-prefill (prefix-cache hit, ISSUE 17) and decode-kernel
#: dispatch (ISSUE 18) steps
DEFAULT_TRAIN_STRATEGIES = ("psum_bucket", "zero1")


def run_default_audits(n_data: int = 4) -> list[dict]:
    """Audit the default artifact set; raise :class:`HLOAuditError` on
    any violation (the CLI maps this to exit 1; the completed reports
    ride on the exception's ``reports`` attribute so the CLI can still
    publish the artifact that shows WHAT failed)."""
    import os

    # the device-count fix must land BEFORE the first backend touch —
    # jax.devices() initializes the backend and latches the count, after
    # which force_host_devices is a no-op for this process
    if "--xla_force_host_platform_device_count=" \
            not in os.environ.get("XLA_FLAGS", ""):
        from theanompi_tpu.parallel.mesh import force_host_devices

        force_host_devices(max(n_data, 8))

    import jax

    if len(jax.devices()) < n_data:
        raise HLOAuditError(
            f"need {n_data} devices for the train-step audit, have "
            f"{len(jax.devices())} — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_data} "
            f"before jax initializes")
    reports = [audit_train_step(s, n_data) for s in DEFAULT_TRAIN_STRATEGIES]
    # the overlap audits run at n_data=2 (the signature default, shared
    # with the test suite's lru entries): the chain/interleave facts are
    # device-count-independent and the fused-vs-overlapped comparison is
    # at matched n, so extra devices only add compile time
    reports += [audit_overlap_schedule(s)
                for s in DEFAULT_OVERLAP_STRATEGIES]
    reports.append(audit_serve_step())
    reports.append(audit_serve_prefill())
    reports.append(audit_serve_decode_kernel())
    bad = [r for r in reports if not r["ok"]]
    if bad:
        err = HLOAuditError("; ".join(
            f"[{r['kind']}:{r.get('strategy', 'decode')}] {v}"
            for r in bad for v in r["violations"]))
        err.reports = reports
        raise err
    return reports
