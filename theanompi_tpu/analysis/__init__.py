"""theanompi_tpu.analysis — the ``tmlint`` static-analysis subsystem.

Two halves:

- **AST rules** (:mod:`.core`, :mod:`.rules`, :mod:`.layers`): a rule
  registry run over one shared parse per file — wall-clock discipline,
  exception swallowing, np.load confinement, donated-buffer escapes,
  host syncs in spans, jit nondeterminism, exit-code literals, and the
  declared package-layer DAG.  Console script: ``tmlint``.
- **Compiled-artifact audit** (:mod:`.hlo_audit`): jit representative
  train/serve steps and assert what the AST cannot see — donation
  actually applied, the PR 2 collective-count lock, no host callbacks
  in the HLO.

Import surface is deliberately lazy: ``from theanompi_tpu.analysis import
core`` pulls no jax; only ``hlo_audit`` needs a backend.
"""

from theanompi_tpu.analysis.core import (  # noqa: F401
    Finding,
    Rule,
    all_rules,
    build_report,
    default_paths,
    lint_paths,
    register,
)
