"""theanompi_tpu.analysis — the ``tmlint`` static-analysis subsystem.

Three halves:

- **AST rules** (:mod:`.core`, :mod:`.rules`, :mod:`.layers`): a rule
  registry run over one shared parse per file — wall-clock discipline,
  exception swallowing, np.load confinement, donated-buffer escapes,
  host syncs in spans, jit nondeterminism, exit-code literals, the
  declared package-layer DAG, and the ISSUE 15 concurrency tier
  (atomic-publish, guarded-state, thread-lifecycle, lock-order).
  Console script: ``tmlint``.
- **Compiled-artifact audit** (:mod:`.hlo_audit`): jit representative
  train/serve steps and assert what the AST cannot see — donation
  actually applied, the PR 2 collective-count lock, no host callbacks
  in the HLO.
- **Interleaving harness** (:mod:`.interleave`): ``sp(name)``
  sync-points threaded through the thread seams (checkpoint writer,
  fleet passes, health ticker), a deterministic scheduler that replays
  exact interleavings, and the ``tmlint --race-audit`` negative proof
  that the harness still detects the seeded lost-update race.

Import surface is deliberately lazy: ``from theanompi_tpu.analysis import
core`` pulls no jax; only ``hlo_audit`` needs a backend (``interleave``
is stdlib-only — it sits at the bottom of the layer DAG so the
instrumented seams can import it).
"""

from theanompi_tpu.analysis.core import (  # noqa: F401
    Finding,
    Rule,
    all_rules,
    build_report,
    default_paths,
    lint_paths,
    register,
)
