"""tmlint core: rule registry, one-parse-per-file engine, suppressions.

The three ad-hoc AST walkers that grew inside ``tests/test_lint_*.py``
(PR 1's wall-clock lint, PR 4's exception-swallowing lint, PR 5's np.load
confinement) each re-implemented the same loop: glob the package, read,
parse, walk, collect offender strings.  This module is that loop, once:

- a file is read and ``ast.parse``'d exactly ONCE per run (``SourceFile``),
  shared by every rule — adding a rule costs a visitor, not a parse;
- rules are small classes registered by name (:func:`register`), each
  yielding :class:`Finding`\\ s with a severity and a one-line message;
- suppression is inline and self-documenting: ``# lint: <rule>-ok — why``
  on the flagged line.  The justification text is REQUIRED — a bare
  marker is itself a finding (rule ``suppression``), as is a marker
  naming a rule that does not exist.  Nothing is suppressed invisibly:
  suppressed findings ride the JSON report under ``"suppressed"``.

The engine is import-light (stdlib only) so ``tmlint`` runs in any
environment the repo's tests run in; the compiled-artifact auditor
(:mod:`theanompi_tpu.analysis.hlo_audit`), which needs jax, stays a
separate module.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable, Iterator

#: repository root (the directory holding ``theanompi_tpu/`` and bench.py)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SEV_ERROR = "error"
SEV_WARNING = "warning"
_SEVERITIES = (SEV_ERROR, SEV_WARNING)

#: suppression marker grammar: ``# lint: <rule>-ok <justification>``.
#: The justification may be set off with ``—``, ``--`` or ``:`` and must be
#: non-empty; ``tmlint`` verifies both the rule name and the justification.
#: The marker must START its comment (``# lint: ...``) — a prose mention
#: of the grammar mid-sentence neither suppresses nor trips the meta rule.
_MARKER_RE = re.compile(r"#\s*lint:\s*([A-Za-z0-9_-]+)-ok\b(.*)")
_SEP_RE = re.compile(r"^[\s—:,-]+")

#: marker rule id for suppression-grammar violations (bare marker, unknown
#: rule name) — not a registered Rule: it cannot itself be suppressed.
META_RULE = "suppression"


@dataclasses.dataclass
class Finding:
    """One lint offence, pointing at a source line."""

    rule: str
    severity: str
    path: str        # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: str | None = None

    def format(self) -> str:
        tail = (f"  [suppressed: {self.justification}]"
                if self.suppressed else "")
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.severity} [{self.rule}] {self.message}{tail}")

    def as_json(self) -> dict:
        d = dataclasses.asdict(self)
        if not self.suppressed:
            d.pop("justification")
        return d


@dataclasses.dataclass
class Suppression:
    """A parsed ``# lint: <rule>-ok`` marker on one line."""

    rule: str
    line: int
    justification: str


class SourceFile:
    """One parsed python file: text, lines, AST and suppression markers —
    computed once, shared by every rule in the run."""

    def __init__(self, path: str, root: str = REPO_ROOT):
        self.path = os.path.abspath(path)
        self.rel = os.path.relpath(self.path, root).replace(os.sep, "/")
        self.text = open(self.path, encoding="utf-8").read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        self._parents: dict[ast.AST, ast.AST] | None = None
        #: line -> list of markers on that line
        self.markers: dict[int, list[Suppression]] = {}
        for lineno, line in enumerate(self.lines, start=1):
            for m in _MARKER_RE.finditer(line):
                just = _SEP_RE.sub("", m.group(2)).strip()
                self.markers.setdefault(lineno, []).append(
                    Suppression(m.group(1), lineno, just))

    def parent_map(self) -> dict[ast.AST, ast.AST]:
        """child -> parent for the whole tree (built lazily, once)."""
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        parents = self.parent_map()
        while node in parents:
            node = parents[node]
            yield node

    def marker_for(self, rule: str, lines: Iterable[int]) -> Suppression | None:
        """The first ``<rule>-ok`` marker on any of ``lines`` (a rule may
        accept the marker on more than one line, e.g. the ``except`` line
        or its first body line)."""
        for lineno in lines:
            for sup in self.markers.get(lineno, ()):
                if sup.rule == rule:
                    return sup
        return None


class Rule:
    """A registered lint rule: a named check over one :class:`SourceFile`.

    Subclasses set ``name``/``severity``/``description`` and implement
    :meth:`check`, yielding findings via :meth:`finding`.  ``marker_lines``
    lets a rule accept its suppression marker on lines other than the
    flagged one (the swallow rule honours the first handler-body line,
    matching the PR 4 marker placement).
    """

    name: str = ""
    severity: str = SEV_ERROR
    description: str = ""

    def check(self, src: SourceFile) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, src: SourceFile, line: int, col: int, message: str,
                marker_lines: Iterable[int] = ()) -> Finding:
        f = Finding(self.name, self.severity, src.rel, line, col, message)
        # the marker counts on the flagged line, on rule-specific extra
        # lines, or on a contiguous pure-comment block immediately above
        # (where justifications go when the flagged line has no room)
        cand = [line, *marker_lines]
        prev = line - 1
        while 0 < prev <= len(src.lines) \
                and src.lines[prev - 1].lstrip().startswith("#"):
            cand.append(prev)
            prev -= 1
        sup = src.marker_for(self.name, cand)
        if sup is not None and sup.justification:
            f.suppressed = True
            f.justification = sup.justification
        return f


_REGISTRY: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    """Class decorator: add a rule to the global registry (name-keyed)."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if cls.severity not in _SEVERITIES:
        raise ValueError(f"rule {cls.name}: bad severity {cls.severity!r}")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> dict[str, type[Rule]]:
    """name -> rule class, importing the built-in rule modules on first
    use (registration happens at import time)."""
    from theanompi_tpu.analysis import layers, rules  # noqa: F401

    return dict(_REGISTRY)


def default_paths(root: str = REPO_ROOT) -> list[str]:
    """What ``tmlint`` scans with no path arguments: the package and the
    bench entrypoint — the exact coverage the legacy test lints had."""
    paths = []
    pkg = os.path.join(root, "theanompi_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames.sort()
        for f in sorted(filenames):
            if f.endswith(".py"):
                paths.append(os.path.join(dirpath, f))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    return paths


def _meta_findings(src: SourceFile, known: set[str]) -> Iterator[Finding]:
    """Suppression-grammar violations: unknown rule name, or a marker with
    no justification.  These are never themselves suppressible."""
    for lineno, sups in sorted(src.markers.items()):
        for sup in sups:
            if sup.rule not in known:
                yield Finding(
                    META_RULE, SEV_ERROR, src.rel, lineno, 0,
                    f"suppression names unknown rule {sup.rule!r} "
                    f"(known: {', '.join(sorted(known))})")
            elif not sup.justification:
                yield Finding(
                    META_RULE, SEV_ERROR, src.rel, lineno, 0,
                    f"suppression 'lint: {sup.rule}-ok' carries no "
                    f"justification — say WHY the exception is safe")


def lint_paths(paths: Iterable[str] | None = None,
               rule_names: Iterable[str] | None = None,
               root: str = REPO_ROOT,
               on_file: Callable[[str], None] | None = None,
               ) -> tuple[list[Finding], int]:
    """Run rules over files; -> (all findings incl. suppressed, n_files).

    ``rule_names=None`` runs every registered rule.  Suppression-grammar
    checks always run: a stale or bare marker is a finding even when the
    rule it names was deselected (otherwise ``--rules wall`` would hide a
    broken ``swallow-ok`` marker from CI).
    """
    registry = all_rules()
    if rule_names is None:
        selected = sorted(registry)
    else:
        selected = list(rule_names)
        unknown = [r for r in selected if r not in registry]
        if unknown:
            raise KeyError(
                f"unknown rule(s) {', '.join(unknown)} "
                f"(known: {', '.join(sorted(registry))})")
    rules_ = [registry[n]() for n in selected]
    known = set(registry)
    findings: list[Finding] = []
    n_files = 0
    for path in (default_paths(root) if paths is None else paths):
        if on_file is not None:
            on_file(path)
        src = SourceFile(path, root=root)
        n_files += 1
        for rule in rules_:
            findings.extend(rule.check(src))
        findings.extend(_meta_findings(src, known))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, n_files


def build_report(findings: list[Finding], n_files: int,
                 rule_names: Iterable[str] | None = None) -> dict:
    """The ``--report`` JSON artifact (schema locked by test)."""
    registry = all_rules()
    names = sorted(registry) if rule_names is None else list(rule_names)
    active = [f for f in findings if not f.suppressed]
    return {
        "version": 1,
        "tool": "tmlint",
        "files_scanned": n_files,
        "rules": [
            {"name": n, "severity": registry[n].severity,
             "description": registry[n].description}
            for n in names
        ],
        "findings": [f.as_json() for f in active],
        "suppressed": [f.as_json() for f in findings if f.suppressed],
        "summary": {
            "errors": sum(f.severity == SEV_ERROR for f in active),
            "warnings": sum(f.severity == SEV_WARNING for f in active),
            "suppressed": sum(f.suppressed for f in findings),
        },
    }


def write_report(report: dict, path: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
