"""``tmlint`` — the static-analysis console script.

Shares the repo's one-line-error exit contract (tmlauncher/tmserve):

- ``0`` — clean: no unsuppressed findings;
- ``1`` — findings (each printed ``path:line:col: severity [rule] msg``);
- ``2`` — usage error (unknown rule, bad path), one ``tmlint: error:``
  stderr line (argparse's own exit 2 for bad flags is kept).

``--report FILE`` writes the JSON artifact (schema locked by test);
``--hlo-audit`` additionally runs the compiled-artifact auditor, which
needs jax and a few seconds of XLA compile — the plain AST run stays
dependency-light and fast for pre-commit use.  ``--race-audit`` runs
the interleaving harness's negative proof (pure Python, no jax): the
seeded lost-update race must be detected and its lock-guarded twin
must stay clean, or the run exits 1.
"""

from __future__ import annotations

import argparse
import json
import sys

from theanompi_tpu.analysis import core


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmlint",
        description="JAX-aware static analysis for theanompi_tpu "
                    "(rule registry + compiled-artifact auditor)",
        allow_abbrev=False)
    p.add_argument("paths", nargs="*",
                   help="files to lint (default: the package + bench.py)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    p.add_argument("--report", default=None, metavar="FILE",
                   help="write the JSON report artifact to FILE")
    p.add_argument("--hlo-audit", action="store_true",
                   help="also audit compiled train/serve steps (donation, "
                        "collective counts, host callbacks; needs jax)")
    p.add_argument("--race-audit", action="store_true",
                   help="run the interleaving harness self-check: the "
                        "seeded synthetic race must be detected, the "
                        "guarded twin must stay clean (pure Python)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="print suppressed findings too (always in --report)")
    p.add_argument("--quiet", action="store_true",
                   help="summary line only, no per-finding output")
    return p


def _error_line(what: str, err: BaseException | str) -> None:
    print(f"tmlint: error: {what}: {err}", file=sys.stderr, flush=True)


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on bad flags — keep its contract
        return int(e.code or 0)

    if args.list_rules:
        for name, cls in sorted(core.all_rules().items()):
            print(f"{name:16s} {cls.severity:8s} {cls.description}")
        return 0

    rule_names = (None if args.rules is None
                  else [r.strip() for r in args.rules.split(",") if r.strip()])
    paths = args.paths or None
    try:
        findings, n_files = core.lint_paths(paths, rule_names)
    except KeyError as e:
        _error_line("rules", e.args[0])
        return 2
    except (OSError, SyntaxError) as e:
        _error_line("paths", e)
        return 2
    except Exception as e:
        _error_line("internal", e)
        return 2

    audit_reports = None
    audit_failure = None
    if args.hlo_audit:
        from theanompi_tpu.analysis import hlo_audit

        try:
            audit_reports = hlo_audit.run_default_audits()
        except hlo_audit.HLOAuditError as e:
            # a locked-invariant violation is a FINDING, not a usage
            # error: keep going so the AST findings still print and the
            # report artifact (which shows what failed) still publishes
            audit_failure = str(e)
            audit_reports = getattr(e, "reports", None)
        except Exception as e:
            _error_line("hlo-audit", e)
            return 2

    race_report = None
    race_failure = None
    if args.race_audit:
        from theanompi_tpu.analysis import interleave

        try:
            race_report = interleave.race_audit()
        except interleave.RaceAuditError as e:
            # same contract as --hlo-audit: a failed negative proof is a
            # FINDING (the harness lost its teeth), not a usage error
            race_failure = str(e)
            race_report = getattr(e, "report", None)
        except Exception as e:
            _error_line("race-audit", e)
            return 2

    active = [f for f in findings if not f.suppressed]
    if not args.quiet:
        for f in findings:
            if f.suppressed and not args.show_suppressed:
                continue
            print(f.format())
    n_sup = sum(f.suppressed for f in findings)
    print(f"tmlint: {len(active)} finding(s), {n_sup} suppressed, "
          f"{n_files} file(s) scanned"
          + (f", {len(audit_reports)} compiled artifact(s) audited"
             if audit_reports is not None else ""))
    if audit_failure is not None:
        _error_line("hlo-audit", audit_failure)
    if race_report is not None and race_failure is None:
        print(f"tmlint: race-audit: seeded race detected in "
              f"{race_report['racy_lost_updates']}/"
              f"{race_report['orderings']} orderings; guarded twin clean")
    if race_failure is not None:
        _error_line("race-audit", race_failure)

    if args.report:
        report = core.build_report(
            findings, n_files,
            sorted(core.all_rules()) if rule_names is None else rule_names)
        if audit_reports is not None:
            report["hlo_audit"] = audit_reports
        if audit_failure is not None:
            report["hlo_audit_error"] = audit_failure
        if race_report is not None:
            report["race_audit"] = race_report
        if race_failure is not None:
            report["race_audit_error"] = race_failure
        try:
            core.write_report(report, args.report)
        except OSError as e:
            _error_line("report", e)
            return 2
        if not args.quiet:
            print(f"tmlint: report written to {args.report}")

    return 1 if active or audit_failure or race_failure else 0


if __name__ == "__main__":
    raise SystemExit(main())
