"""Parallelism layer: mesh runtime, exchanger strategies, and training rules.

TPU-native replacement for the reference's process/communication layer
(reference, unverified — SURVEY.md §1: ``theanompi/lib/base.py`` [MPI_GPU_process],
``theanompi/lib/exchanger.py``, ``theanompi/lib/exchanger_strategy.py``, plus the
per-rule worker scripts ``bsp_worker.py`` / ``easgd_*.py`` / ``gosgd_worker.py``).
"""

from theanompi_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    Precision,
    make_mesh,
    replica_rng,
    shard_map,
)
from theanompi_tpu.parallel.exchanger import Exchanger, STRATEGIES

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "Precision",
    "make_mesh",
    "replica_rng",
    "shard_map",
    "Exchanger",
    "STRATEGIES",
]
