"""Comm/compute overlap for the bucketed exchange + the quantization ramp.

Overlap (the ``exch_overlap`` rule key)
---------------------------------------

The bucketed strategies pack gradients into a handful of fused flat
buffers and issue one collective per bucket.  With the stock schedule the
buckets are mutually independent dataflow, so XLA is free to sink every
collective to the end of the step — all of backward runs, THEN all
all-reduces fire back-to-back, serializing comm after compute exactly
like the pre-bucketing leaf-wise path did.

``exch_overlap=True`` pins the *issue order* instead: buckets are walked
in REVERSE layout order (backward produces the last layers' gradients
first — the same readiness heuristic as PyTorch DDP's bucket ordering)
and each bucket's packed buffer is given a data dependency on the
previous bucket's reduction result via :func:`fence`.  The chain means
collective k+1 cannot be scheduled before collective k has issued, so
the scheduler interleaves bucket k's collective with the backward
fusions that produce bucket k+1 — comm rides under compute instead of
trailing it.

Why a ``select`` fence and not ``lax.optimization_barrier``: on the CPU
backend the barrier survives lowering to StableHLO but is *stripped* by
the XLA optimization pipeline — it leaves no ordering constraint and no
auditable trace in the optimized module.  The select fence below is real
dataflow: it survives every pass on every backend, and the resulting
collective→collective dependency edges are exactly what
``analysis/hlo_audit.py`` measures to prove the schedule
(:func:`theanompi_tpu.analysis.hlo_audit.audit_overlap_schedule`).

Bit-equality contract: the fence's predicate is true at runtime, so
``select`` returns the bucket buffer verbatim — the overlapped path
produces bit-identical parameters to the fused path (locked in
``tests/test_overlap.py``).  The predicate must be *opaque* to the
compiler: ``step >= 0`` on the traced int32 step scalar works because
XLA cannot prove a signed runtime parameter non-negative, while a
constant-true predicate (or ``x - x`` / ``0 * probe`` style no-ops)
would be folded away and dissolve the chain.

Quantization ramp (the ``exch_ramp`` rule key)
----------------------------------------------

Early training tolerates coarse gradients; late training does not.
:class:`RampSchedule` parses a spec like ``"ring_int8:5,psum_bf16_bucket:10"``
— int8 wire for epochs [0, 5), bf16 for [5, 10), then the base strategy —
and the trainer swaps the exchanger at *epoch boundaries only* (one
fenced recompile per phase, never a per-step recompile storm).  Resume
derives the active phase from the restored absolute epoch, so a mid-ramp
checkpoint restarts in the right phase with no extra state.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax


def overlap_pred(step):
    """The opaque always-true predicate anchoring the fence chain.

    ``step`` is the traced int32 step scalar threaded through the train
    step.  ``step >= 0`` holds at runtime but is not provable at compile
    time for a signed parameter (``step >= INT32_MIN`` *would* be folded),
    so the fence's false branch — and with it the dependency edge —
    survives optimization.
    """
    return step >= jnp.int32(0)


def fence(buf, prev, pred):
    """Give ``buf`` a value-preserving data dependency on ``prev``.

    ``pred`` is always true at runtime (see :func:`overlap_pred`), so the
    select returns ``buf`` bit-exactly; the false branch folds one element
    of ``prev`` in, which is what makes ``buf`` depend on ``prev`` in the
    optimized HLO.  Cost: one fused select+add per bucket — noise next to
    the collective it orders.
    """
    probe = lax.slice_in_dim(prev.reshape(-1), 0, 1)[0]
    return lax.select(jnp.broadcast_to(pred, buf.shape),
                      buf, buf + probe.astype(buf.dtype))


@dataclasses.dataclass(frozen=True)
class RampSchedule:
    """Epoch-indexed exchange-strategy phases parsed from ``exch_ramp``.

    ``phases`` is ``((strategy, until_epoch), ...)`` — each phase is
    active for epochs ``< until_epoch`` — followed by the base strategy
    for every remaining epoch (``until_epoch`` None).  Boundaries are
    strictly increasing; the phase for an epoch is a pure function of the
    absolute epoch number, which is what makes checkpoint resume restore
    the right phase for free.
    """

    phases: tuple  # ((strategy, until_epoch | None), ...); last is the base

    @classmethod
    def parse(cls, spec: str, base_strategy: str) -> "RampSchedule":
        """Parse ``"strategy:until_epoch,..."`` (e.g. ``"ring_int8:5"``).

        ``zero1`` is refused anywhere in a ramp — its optimizer state
        lives in the exchanger's sharded bucket layout, so swapping into
        or out of it mid-run would require re-laying-out opt state.
        """
        from theanompi_tpu.parallel.exchanger import (
            BUCKETED_STRATEGIES, STRATEGIES)

        known = set(STRATEGIES) | set(BUCKETED_STRATEGIES)
        phases = []
        last_until = 0
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise ValueError(
                    f"exch_ramp phase {part!r} must be 'strategy:until_epoch'")
            name, until_s = part.rsplit(":", 1)
            name = name.strip()
            try:
                until = int(until_s)
            except ValueError:
                raise ValueError(
                    f"exch_ramp boundary {until_s!r} is not an epoch number")
            if name not in known:
                raise ValueError(
                    f"unknown exch_ramp strategy {name!r}; "
                    f"available: {sorted(known)}")
            if until <= last_until:
                raise ValueError(
                    f"exch_ramp boundaries must be strictly increasing; "
                    f"got {until} after {last_until}")
            phases.append((name, until))
            last_until = until
        if not phases:
            raise ValueError(f"empty exch_ramp spec {spec!r}")
        for name, _ in phases + [(base_strategy, None)]:
            if name == "zero1":
                raise ValueError(
                    "zero1 cannot participate in an exch_ramp: its optimizer "
                    "state is laid out in the exchanger's sharded buckets and "
                    "cannot be re-laid-out at a phase boundary")
        phases.append((base_strategy, None))
        return cls(phases=tuple(phases))

    @property
    def strategies(self) -> tuple:
        """Every strategy the ramp can activate, in phase order."""
        seen, out = set(), []
        for name, _ in self.phases:
            if name not in seen:
                seen.add(name)
                out.append(name)
        return tuple(out)

    def phase_for_epoch(self, epoch: int) -> int:
        for i, (_, until) in enumerate(self.phases):
            if until is None or epoch < until:
                return i
        return len(self.phases) - 1

    def strategy_for_epoch(self, epoch: int) -> str:
        return self.phases[self.phase_for_epoch(epoch)][0]

    def describe(self) -> str:
        """Stable string for the run fingerprint."""
        return ",".join(
            name if until is None else f"{name}:{until}"
            for name, until in self.phases)
