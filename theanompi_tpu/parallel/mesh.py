"""Device mesh runtime: discovery, mesh construction, precision policy, RNG.

This is the TPU-native analogue of the reference's process bootstrap
(reference, unverified — SURVEY.md §2.1: ``theanompi/lib/base.py`` class
``MPI_GPU_process``: binds one GPU per OS process via ``theano.gpuarray``,
builds an ``MPI.COMM_WORLD`` plus an intra-node NCCL clique).  On TPU there is
no per-device process and no explicit communicator object: a single controller
builds a :class:`jax.sharding.Mesh` over the chips, and XLA lowers collective
ops over its named axes to ICI/DCN traffic.  "Binding a device" becomes
"naming a mesh axis"; the NCCL clique becomes the mesh itself.

Axes convention:

- ``data``  — data parallelism (the reference's only parallelism; one worker
  per reference GPU maps to one slice along this axis),
- ``pipe``  — pipeline parallelism over stacked homogeneous blocks
  (see :mod:`theanompi_tpu.parallel.pipeline`),
- ``model`` — tensor parallelism (beyond reference capability, here from day
  one so shardings compose),
- ``seq``   — sequence/context parallelism for ring attention
  (see :mod:`theanompi_tpu.parallel.ring_attention`).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
PIPE_AXIS = "pipe"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"

if not hasattr(jax.lax, "axis_size"):
    # jax < 0.5 has no lax.axis_size; psum of a Python literal is computed
    # statically inside the collective context and raises the same
    # NameError on an unbound axis, so callers (exchanger, axis_bound)
    # behave identically.  Installed on jax.lax so every module that spells
    # ``lax.axis_size`` works unmodified.
    jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)


def force_host_devices(n: int) -> None:
    """Force ``n`` virtual CPU devices.  Must run before the first backend init.

    The test-suite analogue of the reference's multi-GPU cluster: the reference
    could only be tested on a real CUDA+MPI cluster (SURVEY.md §4); we fake an
    ``n``-chip mesh on host CPU so every collective path is unit-testable.

    Handles both late-env pitfalls: an existing device-count flag is replaced
    (not silently kept), and because this image's sitecustomize imports jax at
    interpreter start with ``JAX_PLATFORMS`` baked into config defaults, the
    platform is forced via ``jax.config`` rather than the (too-late) env var.
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags.strip() + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def setup_compile_cache(directory: str | None = None,
                        min_compile_secs: float | None = None) -> str | None:
    """Wire JAX's persistent compilation cache (ISSUE 3 platform setup).

    Without it every restart, resume, and scaling-sweep subprocess repays
    the full XLA compile (the PR 1 runbook dry-run measured 370 s,
    dominated by compile).  With a shared ``directory``, the first process
    populates it and every later process with identical programs loads the
    compiled executable instead — the trainer's ``compile.first_step_s``
    gauge makes the hit visible.

    ``directory=None`` falls back to the ``THEANOMPI_COMPILE_CACHE`` env
    var; with neither set this is a no-op returning None.  Call before the
    first jit dispatch (config flips are ignored for already-compiled
    programs, not an error).  ``min_compile_secs=None`` (the production
    default — launcher/scaling/bench) keeps jax's own floor (1 s), so a
    pod of hosts does not spray every sub-second helper jit into shared
    storage; the expensive train/eval programs the cache exists for are
    multi-second compiles and persist regardless.  Tests that must observe
    hits on tiny sub-second programs pass an explicit ``0``.
    """
    directory = directory or os.environ.get("THEANOMPI_COMPILE_CACHE")
    if not directory:
        return None
    directory = os.path.abspath(os.path.expanduser(directory))
    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    if min_compile_secs is not None:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        try:
            # -1 disables the entry-size floor (name/semantics exist from
            # jax 0.4.30 on; older jax simply keeps its default)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except AttributeError:  # lint: swallow-ok — version-compat probe
            pass
    try:
        # jax latches "no cache" at the first compile that ran before this
        # config flip (compilation_cache._cache_checked); a reset makes the
        # next compile re-read the config — required whenever anything
        # already jitted in this process (e.g. the test suite's dry-runs)
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:  # lint: swallow-ok — private jax surface; a moved
        pass  # symbol must not break the launcher's cache-flip best effort
    return directory


def make_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    n_seq: int = 1,
    n_pipe: int = 1,
    devices: Sequence[Any] | None = None,
) -> Mesh:
    """Build a ``(data, pipe, model, seq)`` mesh over the available devices.

    ``n_data=None`` consumes all devices left over after the other axes.
    A mesh of total size 1 is valid and is the single-worker ("CPU Theano
    mode", BASELINE.md config 1) case.
    """
    if devices is None:
        devices = jax.devices()
    total = len(devices)
    rest = n_model * n_seq * n_pipe
    if n_data is None:
        if total % rest != 0:
            raise ValueError(
                f"{total} devices not divisible by pipe*model*seq={rest}"
            )
        n_data = total // rest
    need = n_data * rest
    if need > total:
        raise ValueError(f"need {need} devices, have {total}")
    arr = np.asarray(devices[:need], dtype=object).reshape(
        n_data, n_pipe, n_model, n_seq
    )
    return Mesh(arr, (DATA_AXIS, PIPE_AXIS, MODEL_AXIS, SEQ_AXIS))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Sharding that splits the leading (batch) dim over the ``data`` axis."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


@dataclasses.dataclass(frozen=True)
class Precision:
    """Mixed-precision policy: fp32 params, bf16 compute, fp32 outputs.

    The reference's analogue is its fp16 exchange strategies (``asa16``,
    ``nccl16`` — SURVEY.md §2.1, exchanger strategies) plus Theano's
    ``floatX``.  On TPU the MXU natively consumes bf16, so compute-in-bf16 is
    the default rather than a compression trick; the exchange-compression
    analogue lives in :mod:`theanompi_tpu.parallel.exchanger` (``psum_bf16``).
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    output_dtype: Any = jnp.float32

    def cast_to_compute(self, tree, is_leaf=None):
        """``is_leaf`` lets callers fence off opaque pytree nodes the
        policy must pass through whole — the serving fast path's
        ``QuantizedTensor`` leaves carry fp32 scales that must NOT cast
        to bf16 (this layer stays import-free, so the fence is generic)."""
        return jax.tree.map(self._cast(self.compute_dtype), tree,
                            is_leaf=is_leaf)

    def cast_to_param(self, tree):
        return jax.tree.map(self._cast(self.param_dtype), tree)

    def cast_to_output(self, tree):
        return jax.tree.map(self._cast(self.output_dtype), tree)

    @staticmethod
    def _cast(dtype):
        def cast(x):
            # result_type (not isinstance) so numpy arrays and Python floats
            # in a host-initialized params pytree are cast too, instead of
            # silently passing through the policy.  Leaves with no array
            # interpretation (an is_leaf-fenced QuantizedTensor) pass
            # through untouched.
            try:
                if jnp.issubdtype(jnp.result_type(x), jnp.floating):
                    return jnp.asarray(x, dtype)
            except TypeError:  # lint: swallow-ok — non-array leaf (QuantizedTensor), policy passes it through
                pass
            return x

        return cast


#: Full precision everywhere — for CPU tests and numerical-parity checks.
FP32 = Precision(compute_dtype=jnp.float32)
#: TPU default.
BF16 = Precision()


def shard_map(f, mesh: Mesh, in_specs, out_specs, check: bool = False):
    """Thin wrapper over :func:`jax.shard_map` pinning this repo's defaults.

    ``check=False`` disables varying-manual-axes checking: the ring strategies
    (:mod:`theanompi_tpu.parallel.exchanger`) produce replicated outputs via
    ``ppermute`` chains the checker cannot prove replicated.

    Version shim: jax promoted shard_map out of ``jax.experimental`` (and
    renamed ``check_rep`` to ``check_vma``) — support both so the installed
    jax decides which spelling runs.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check
    )


def replica_rng(key: jax.Array, axis_name=DATA_AXIS) -> jax.Array:
    """Derive a distinct PRNG key per replica along one or more mesh axes.

    Call only inside ``shard_map``/collective context.  Replaces the
    reference's per-process numpy seeding (each MPI rank seeded separately;
    SURVEY.md §2.1 base.py) with a deterministic fold of the replica index.
    Pass a tuple (e.g. ``("data", "seq")``) when activations are sharded over
    several axes and per-shard randomness (dropout) must differ on each.
    """
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    for a in axes:
        key = jax.random.fold_in(key, jax.lax.axis_index(a))
    return key
