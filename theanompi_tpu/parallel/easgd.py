"""EASGD: elastic-averaging data parallelism (reference's async rule).

Reference (unverified — SURVEY.md §2.1/§3.3): ``easgd_server.py`` holds center
parameters on its own GPU and services async worker requests; each
``easgd_worker.py`` runs τ local SGD steps then elastically averages with the
center (worker: ``p += α(center − p)``; server: ``center += α(p − center)``),
with LR scaled by worker count (``model.scale_lr``).

TPU-native re-expression: XLA is bulk-synchronous — there is no async
one-sided communication — so the rule becomes its *synchronous periodic*
variant (the EASGD paper's sync form, which the τ-periodic reference already
approximates): every worker keeps its own divergent parameter copy (stacked
along a leading axis sharded over ``data``), runs τ collective-free local
steps, then one collective elastic exchange updates workers and center
together::

    diff_i  = p_i − center
    p_i    ← p_i − α·diff_i
    center ← center + α·Σ_i diff_i

No server chip is sacrificed (the reference dedicated a GPU to the center);
the center is replicated and updated by the same psum that reads the workers.
Semantics preserved: bounded staleness τ, elastic moving rate α, divergent
exploration between exchanges.  Semantics changed: exchanges are mutually
synchronous rounds rather than per-worker-clock asynchronous events.
"""

from __future__ import annotations

import os
import sys
import time
from collections import deque

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map
from theanompi_tpu.parallel.trainer import (
    BaseTrainer,
    Rule,
    make_local_eval,
    make_local_step,
    require_data_parallel_mesh,
    pmean_floats,
    restack,
    stack_for_workers,
    unstack,
)
from theanompi_tpu.telemetry.metrics import (
    ASYNC_GAUGES,
    ASYNC_INSTANTS,
    EXCHANGE_COUNTS,
)
from theanompi_tpu.utils.helper_funcs import replicate

# registered spellings (telemetry/metrics.py is the one source of truth
# the async_staleness detector, tmhealth and the aggregator read from)
_EXCHANGE_INSTANT = ASYNC_INSTANTS[0]                   # easgd.exchange
_STALENESS_GAUGE, _DRIFT_GAUGE = ASYNC_GAUGES[0], ASYNC_GAUGES[1]
_WIRE_BYTES = EXCHANGE_COUNTS[0]


def elastic_exchange(params, center, alpha, axis_name=DATA_AXIS):
    """One synchronous elastic-averaging round (pure, inside shard_map)."""

    def is_float(x):
        return jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)

    new_p = jax.tree.map(
        lambda p, c: p - alpha * (p - c) if is_float(p) else p, params, center
    )
    new_c = jax.tree.map(
        lambda p, c: c + alpha * lax.psum(p - c, axis_name) if is_float(p) else c,
        params,
        center,
    )
    return new_p, new_c


def worker_drift(params, center):
    """This worker's relative divergence from the center,
    ``norm(p - c) / norm(c)`` over the float leaves (pure, inside
    shard_map).  The ISSUE 20 health signal: computed on device at
    exchange boundaries only — between rounds it costs nothing."""
    num = jnp.float32(0.0)
    den = jnp.float32(0.0)
    for p, c in zip(jax.tree.leaves(params), jax.tree.leaves(center)):
        if not jnp.issubdtype(jnp.asarray(p).dtype, jnp.inexact):
            continue
        d = p.astype(jnp.float32) - c.astype(jnp.float32)
        num += jnp.sum(d * d)
        den += jnp.sum(c.astype(jnp.float32) ** 2)
    return jnp.sqrt(num) / jnp.maximum(jnp.sqrt(den), 1e-12)


class EASGDTrainer(BaseTrainer):
    """τ local steps per worker, then a collective elastic exchange.

    ``alpha`` defaults to ``0.9 / n_workers``: the EASGD paper (Zhang,
    Choromanska & LeCun, NeurIPS 2015, §5) parameterizes the elastic force
    as ``β = p·α`` and uses ``β = 0.9`` in all experiments, giving
    ``α = 0.9/p`` for ``p`` workers.  (The reference's own default is
    unrecoverable — its mount is empty — so the paper is the source.)
    """

    def __init__(self, model, mesh=None, tau: int = 4,
                 alpha: float | None = None, **kwargs):
        super().__init__(model, mesh=mesh, **kwargs)
        require_data_parallel_mesh(self.mesh, "EASGDTrainer")
        self.tau = tau
        # keep the CONFIGURED value apart from the derived one: the
        # fingerprint stamps the config ("auto" when defaulted), so the
        # n-dependent default never pins a lineage to one worker count
        self._alpha_cfg = alpha
        self.alpha = alpha if alpha is not None else 0.9 / self.n_workers
        self.center = None
        self._exchange_fn = None
        self._consensus_state_fn = None
        self._elastic_wire_bytes: int | None = None
        # ISSUE 20 round bookkeeping: ordinal (the easgd fault-site index),
        # staleness anchor, and a wall-interval window for the stretch
        # signal the async_staleness detector consumes
        self._exchange_count = 0
        self._last_exchange_iter = 0
        self._exchange_intervals: deque = deque(maxlen=16)
        self._last_exchange_t: float | None = None

    def _exchange_pair(self, params, center):
        """The periodic exchange, on UNSTACKED per-worker params; the
        local-SGD control subclass overrides this single hook."""
        new_p, new_c = elastic_exchange(unstack(params), center, self.alpha)
        return restack(new_p), new_c

    def compile_iter_fns(self) -> None:
        local_step = make_local_step(
            self.model, self.optimizer, jax.random.PRNGKey(self.seed),
            stacked=True,
            # per-worker guard (no exchanger => no cross-worker reduction,
            # which matches the rule: params are per-worker divergent)
            sentinel_skip=(self.sentinel is not None
                           and self.sentinel.device_guard),
        )
        local_eval = make_local_eval(self.model)

        def exchange(params, center):
            # drift against the PRE-round center: the divergence the τ
            # local steps accumulated, which this round is about to relax
            drift = worker_drift(unstack(params), center)
            new_p, new_c = self._exchange_pair(params, center)
            return new_p, new_c, drift[None]

        def consensus_state(state):
            return pmean_floats(unstack(state), DATA_AXIS)

        W = P(DATA_AXIS)
        self._step_fn = jax.jit(
            shard_map(
                local_step,
                self.mesh,
                in_specs=(W, W, W, W, P(), P()),
                out_specs=(W, W, W, W),
            ),
            donate_argnums=(0, 1, 2),
        )
        self._exchange_fn = jax.jit(
            shard_map(exchange, self.mesh, in_specs=(W, P()),
                      out_specs=(W, P(), W)),
            donate_argnums=(0, 1),
        )
        self._eval_fn = jax.jit(
            shard_map(
                local_eval, self.mesh, in_specs=(P(), P(), W), out_specs=P()
            )
        )
        self._consensus_state_fn = jax.jit(
            shard_map(consensus_state, self.mesh, in_specs=(W,), out_specs=P())
        )

    def init_state(self) -> None:
        params, state = self.model.init_params(jax.random.PRNGKey(self.seed + 1))
        n = self.n_workers
        self.params = stack_for_workers(self.mesh, params, n)
        self.state = stack_for_workers(self.mesh, state, n)
        self.opt_state = stack_for_workers(self.mesh, self.model.init_opt_state(self.optimizer, params), n)
        self.center = replicate(self.mesh, params)

    def post_step(self) -> None:
        if self.iteration % self.tau != 0:
            return
        ordinal = self._exchange_count
        self._exchange_count += 1
        if self.fault_plan is not None \
                and self.fault_plan.fire("easgd", ordinal, "worker_slow"):
            # ISSUE 20 straggler site: stall the host before the collective
            # — the synchronous round waits, so throughput degrades while
            # the exchange math (and therefore the trajectory) is untouched
            slow_s = float(os.environ.get("THEANOMPI_EASGD_SLOW_S", "0.5"))
            print(f"faults: injected EASGD straggler: round {ordinal} "
                  f"stalls {slow_s:g}s", file=sys.stderr, flush=True)
            time.sleep(slow_s)
        self.recorder.start("comm")
        self.params, self.center, drift = self._exchange_fn(
            self.params, self.center)
        self.recorder.end("comm")
        staleness = self.iteration - self._last_exchange_iter
        self._last_exchange_iter = self.iteration
        now = time.perf_counter()
        stretch = 0.0
        if self._last_exchange_t is not None:
            interval = now - self._last_exchange_t
            if self._exchange_intervals:
                base = sorted(self._exchange_intervals)[
                    len(self._exchange_intervals) // 2]
                if base > 0:
                    stretch = interval / base
            self._exchange_intervals.append(interval)
        self._last_exchange_t = now
        if self.telemetry is not None:
            # iteration was already advanced by train_iter: the
            # exchange belongs to the step just finished, whose
            # train.step span is tagged with the pre-increment index
            self.telemetry.count(
                _WIRE_BYTES, self._periodic_wire_bytes(),
                emit=True, step=self.iteration - 1)
            # the scalar pull syncs on the round's outputs — once per
            # round, never per step
            drift_max = float(jnp.max(drift))
            self.telemetry.instant(
                _EXCHANGE_INSTANT, step=self.iteration - 1,
                staleness=int(staleness), expected=int(self.tau),
                stretch=round(stretch, 3), drift=round(drift_max, 6))
            self.telemetry.metrics.gauge(_STALENESS_GAUGE, staleness)
            self.telemetry.metrics.gauge(_DRIFT_GAUGE, drift_max)

    def _periodic_wire_bytes(self) -> int:
        """Static ICI accounting for one elastic round: the only collective
        is the ``psum(p - c)`` over one params-sized tree (see
        :func:`elastic_exchange`) — ring traffic of that buffer.  Payload
        sizing goes through the ISSUE 2 per-dtype contract
        (:func:`~theanompi_tpu.parallel.exchanger.wire_itemsize`): the
        elastic psum moves ``p - c`` in each leaf's OWN dtype — no bf16/int8
        wire compression — so every float leaf counts verbatim."""
        if self._elastic_wire_bytes is None:
            from theanompi_tpu.parallel.exchanger import (
                collective_wire_bytes,
                wire_itemsize,
            )

            total = sum(
                leaf.size * wire_itemsize("elastic", leaf.dtype)
                for leaf in jax.tree.leaves(self.center)
                if jnp.issubdtype(leaf.dtype, jnp.inexact)
            )
            self._elastic_wire_bytes = collective_wire_bytes(
                total, self.n_workers)
        return self._elastic_wire_bytes

    def warmup_exchange(self) -> None:
        self.params, self.center, _ = self._exchange_fn(
            self.params, self.center)

    def eval_args(self):
        """Validate with the center parameters (the reference server's job)."""
        return self.center, self._consensus_state_fn(self.state)

    def checkpoint_trees(self) -> dict:
        return {**super().checkpoint_trees(), "center": self.center}

    def _fingerprint_extra(self) -> dict:
        """ISSUE 20: rule-typed manifest stamp.  ``rule`` is the stacked
        LAYOUT tag (the reshard planner keys its per-worker re-layout on
        it; the trainer class itself already rides the ``exchange`` key);
        ``alpha`` is the CONFIGURED value — ``"auto"`` stays ``"auto"``
        across an elastic mesh8->4 resume, while an explicitly pinned
        alpha (like tau) refuses to silently change mid-lineage."""
        return {
            "rule": "easgd",
            "tau": int(self.tau),
            "alpha": ("auto" if self._alpha_cfg is None
                      else float(self._alpha_cfg)),
        }


class LocalSGDTrainer(EASGDTrainer):
    """Local SGD / periodic parameter averaging: τ collective-free local
    steps, then ``p_i ← mean_j(p_j)`` — "BSP exchanging every τ steps".

    Primarily the EASGD-diagnosis control (VERDICT r3 #8): it shares the
    stacked layout, τ schedule, and exchange cadence with EASGD but
    replaces the elastic force with a plain average.  If this control
    reaches a target at a τ where EASGD fails at every α, the elastic
    coupling is what fails; if neither reaches it, τ-stale exchange itself
    does at that scale.  (Also a useful rule in its own right — the
    k-step-averaging family.)  The ``center`` is kept equal to the average
    so validation-with-center semantics match EASGD's.
    """

    def _exchange_pair(self, params, center):
        avg = pmean_floats(unstack(params), DATA_AXIS)
        return restack(avg), avg


class EASGD(Rule):
    """Elastic-averaging rule.  Config: ``tau``, ``alpha``, ``scale_lr``."""

    trainer_cls = EASGDTrainer
    #: the reference EASGD worker scaled LR by worker count; the local-SGD
    #: control doesn't (its baseline is BSP, which trains at base LR)
    scale_lr_default = True

    def make_trainer(self, model, mesh, recorder) -> EASGDTrainer:
        n = mesh.shape[DATA_AXIS]
        if n > 1 and self.config.get("scale_lr", self.scale_lr_default):
            model.scale_lr(n)  # reference EASGD worker hook
        return self.trainer_cls(
            model,
            mesh=mesh,
            tau=self.config.get("tau", 4),
            alpha=self.config.get("alpha"),
            **self.common_trainer_kwargs(recorder),
        )


class LocalSGD(EASGD):
    """Periodic-averaging rule (the EASGD control).  Config: ``tau``."""

    trainer_cls = LocalSGDTrainer
    scale_lr_default = False
