"""GOSGD: gossip data parallelism (reference's peer-to-peer async rule).

Reference (unverified — SURVEY.md §2.1/§3.4): ``gosgd_worker.py`` — after each
local step every worker draws Bernoulli(p); on success it sends half its
consensus weight ``w_i`` plus its parameters to a uniformly random peer, which
merges ``p_j ← (w_j·p_j + w_i/2·p_i)/(w_j + w_i/2)`` (Blot et al. 2016,
"Gossip training for deep learning").

TPU-native re-expression: the Bernoulli push draws and a random ring shift
``k ∈ {1..n-1}`` are sampled **on host** each round, then one compiled
collective round applies every push at once: pusher ``i``'s target is
``(i+k) mod n`` — marginally uniform over its peers, identical to the
reference's per-worker marginal — and the routing is ``k`` repetitions of the
single-hop ring ``ppermute`` (a ``fori_loop`` with a traced trip count), so a
round costs at most ``n-1`` ICI hops and needs no data-dependent permutation.

Routing-cost tradeoff (deliberate): a shift of ``k`` moves the whole
parameter tree ``k`` sequential hops — O(n) ICI latency worst-case.  The
alternative, one compiled program per shift (each a single direct
``ppermute`` by ``k``), costs one hop per round but ``n-1`` compiled
variants (compile time and HBM for executables scale with n) and loses the
single-trace property.  At gossip's design point — exchanges are rare
(``p_push ~ 1/n``) and overlap compute — hop latency is not the bottleneck,
so one traced program wins; revisit only if profiles show gossip rounds on
the critical path at pod scale.  Weight conservation (Σw = 1) holds by
construction.  Semantics changed:
pushes land at round boundaries instead of asynchronously mid-step, and
within one round targets are a cyclic shift (no collisions) rather than
jointly-iid — the per-worker target distribution is unchanged.
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map
from theanompi_tpu.telemetry.metrics import (
    ASYNC_GAUGES,
    ASYNC_INSTANTS,
    EXCHANGE_COUNTS,
)
from theanompi_tpu.parallel.trainer import (
    BaseTrainer,
    Rule,
    make_local_eval,
    make_local_step,
    require_data_parallel_mesh,
    pmean_floats,
    restack,
    stack_for_workers,
    unstack,
)

# registered spellings (telemetry/metrics.py is the one source of truth
# the async_staleness detector, tmhealth and the aggregator read from)
_ROUND_INSTANT = ASYNC_INSTANTS[1]                      # gosgd.round
_STALE_MAX_GAUGE, _STALE_MEAN_GAUGE = ASYNC_GAUGES[2], ASYNC_GAUGES[3]
_WIRE_BYTES = EXCHANGE_COUNTS[0]


def gossip_merge(params, weight, push, shift, n, axis_name=DATA_AXIS):
    """One gossip round for this worker (pure, inside shard_map).

    ``params``: this worker's pytree; ``weight``: scalar consensus weight;
    ``push``: replicated 0/1 vector ``[n]`` of who pushes; ``shift``: traced
    ring shift — pusher ``i`` targets ``(i+shift) mod n``.  Returns merged
    (params, weight).
    """
    me = lax.axis_index(axis_name)
    my_push = push[me]
    sent_w = my_push * weight * 0.5
    kept_w = weight - sent_w

    is_float = lambda x: jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
    outgoing = [sent_w] + [
        sent_w * leaf.astype(jnp.float32)
        for leaf in jax.tree.leaves(params)
        if is_float(leaf)
    ]
    ring = [(i, (i + 1) % n) for i in range(n)]

    def hop(_, carry):
        return [lax.ppermute(x, axis_name, ring) for x in carry]

    # shift hops of the one-step ring == ppermute by the random shift; the
    # trip count is traced, so one compiled program serves every draw
    incoming = lax.fori_loop(0, shift, hop, outgoing)
    recv_w, recv_leaves = incoming[0], incoming[1:]
    new_w = kept_w + recv_w  # > 0 always: kept_w >= weight/2 > 0

    recv_iter = iter(recv_leaves)

    def merge(leaf):
        if not is_float(leaf):
            return leaf
        merged = (kept_w * leaf.astype(jnp.float32) + next(recv_iter)) / new_w
        return merged.astype(leaf.dtype)

    return jax.tree.map(merge, params), new_w


class GOSGDTrainer(BaseTrainer):
    """Local SGD + host-drawn randomized gossip rounds.

    ``p_push`` is the per-iteration Bernoulli probability (reference default
    semantics; 1/n keeps expected traffic at one push per round).
    """

    def __init__(self, model, mesh=None, p_push: float | None = None, **kwargs):
        super().__init__(model, mesh=mesh, **kwargs)
        require_data_parallel_mesh(self.mesh, "GOSGDTrainer")
        # configured vs derived, same split as EASGD's alpha: the
        # fingerprint stamps the config ("auto" when defaulted), keeping
        # the n-dependent default reshard-compatible
        self._p_push_cfg = p_push
        self.p_push = p_push if p_push is not None else 1.0 / max(self.n_workers, 2)
        self.weights = None
        self._gossip_fn = None
        self._consensus_fn = None
        self._hop_bytes: int | None = None
        # ISSUE 20 round bookkeeping: round ordinal (the gosgd fault-site
        # index) and the per-worker last-participation anchor behind the
        # staleness gauges (lazily re-anchored so a resume never reads as
        # a staleness spike)
        self._round_count = 0
        self._last_touch: np.ndarray | None = None

    def _gossip_hop_bytes(self) -> int:
        """Per-device fp32 bytes one gossip hop moves: the float leaves of
        ONE worker's params (the stacked tree's leading axis is the worker
        count) plus the scalar consensus weight.

        Audited against the ISSUE 2 per-dtype contract
        (:func:`~theanompi_tpu.parallel.exchanger.wire_itemsize`):
        :func:`gossip_merge` explicitly casts every outgoing leaf to fp32
        (``sent_w * leaf.astype(float32)``), so — unlike the bf16/int8 BSP
        strategies — the wire itemsize is 4 for EVERY float leaf, whatever
        its storage dtype; non-float leaves never travel."""
        if self._hop_bytes is None:
            fp32_wire = np.dtype(np.float32).itemsize
            total = fp32_wire  # the ppermuted consensus-weight scalar
            for leaf in jax.tree.leaves(self.params):
                if jnp.issubdtype(leaf.dtype, jnp.inexact):
                    total += leaf.size // self.n_workers * fp32_wire
            self._hop_bytes = total
        return self._hop_bytes

    def _round_draws(self, iteration: int):
        """The (push mask, ring shift) of round ``iteration`` — a pure
        function of (seed, iteration) through the repo's one
        seed-derivation helper, NOT a stateful host RNG: a SIGKILL resume
        at iteration k replays exactly the draws the uninterrupted run
        would have made, so resume bit-equality holds with no extra
        checkpoint state (the old ``RandomState`` carried hidden state no
        checkpoint captured)."""
        from theanompi_tpu.models.data.base import derive_seed

        n = self.n_workers
        rng = np.random.RandomState(
            derive_seed("gossip", self.seed, iteration))
        push = (rng.rand(n) < self.p_push).astype(np.float32)
        shift = int(rng.randint(1, n))
        return push, shift

    def compile_iter_fns(self) -> None:
        local_step = make_local_step(
            self.model, self.optimizer, jax.random.PRNGKey(self.seed),
            stacked=True,
            # per-worker guard, same reasoning as EASGD (params diverge by
            # design, so a per-worker skip cannot desynchronize anything)
            sentinel_skip=(self.sentinel is not None
                           and self.sentinel.device_guard),
        )
        local_eval = make_local_eval(self.model)
        n = self.n_workers

        def gossip(params, weight, push, shift):
            new_p, new_w = gossip_merge(
                unstack(params), unstack(weight), push, shift, n
            )
            return restack(new_p), new_w[None]

        def consensus(params, weight, state):
            params, state = unstack(params), unstack(state)
            w = unstack(weight)

            def avg(leaf):
                if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                    return leaf
                return lax.psum(w * leaf.astype(jnp.float32), DATA_AXIS).astype(
                    leaf.dtype
                )

            return jax.tree.map(avg, params), pmean_floats(state, DATA_AXIS)

        W = P(DATA_AXIS)
        self._step_fn = jax.jit(
            shard_map(
                local_step,
                self.mesh,
                in_specs=(W, W, W, W, P(), P()),
                out_specs=(W, W, W, W),
            ),
            donate_argnums=(0, 1, 2),
        )
        self._gossip_fn = jax.jit(
            shard_map(
                gossip, self.mesh, in_specs=(W, W, P(), P()), out_specs=(W, W)
            ),
            donate_argnums=(0, 1),
        )
        self._eval_fn = jax.jit(
            shard_map(local_eval, self.mesh, in_specs=(P(), P(), W), out_specs=P())
        )
        self._consensus_fn = jax.jit(
            shard_map(
                consensus, self.mesh, in_specs=(W, W, W), out_specs=(P(), P())
            )
        )

    def init_state(self) -> None:
        params, state = self.model.init_params(jax.random.PRNGKey(self.seed + 1))
        n = self.n_workers
        self.params = stack_for_workers(self.mesh, params, n)
        self.state = stack_for_workers(self.mesh, state, n)
        self.opt_state = stack_for_workers(self.mesh, self.model.init_opt_state(self.optimizer, params), n)
        self.weights = jax.device_put(
            np.full((n,), 1.0 / n, np.float32), NamedSharding(self.mesh, P(DATA_AXIS))
        )
        self._round_count = 0
        self._last_touch = None

    def post_step(self) -> None:
        n = self.n_workers
        if n == 1:
            return
        if self._last_touch is None:
            # lazy anchor: init_state runs BEFORE try_resume restores the
            # iteration counter, so anchoring here (first round of this
            # process) keeps post-resume staleness honest
            self._last_touch = np.full((n,), self.iteration - 1, np.int64)
        push, shift = self._round_draws(self.iteration)
        if not push.any():
            return  # no sender drawn this round — skip the collective
        ordinal = self._round_count
        self._round_count += 1
        dropped = (self.fault_plan is not None
                   and self.fault_plan.fire("gosgd", ordinal,
                                            "gossip_drop") is not None)
        if dropped:
            # ISSUE 20 degradation site: the round's collective is skipped
            # — the draws above were already consumed, so the schedule of
            # every later round is unchanged; consensus weights still sum
            # to 1 (nothing moved) and only staleness grows
            print(f"faults: injected gossip drop: round {ordinal} "
                  f"(shift {shift}) skipped", file=sys.stderr, flush=True)
        else:
            self.recorder.start("comm")
            self.params, self.weights = self._gossip_fn(
                self.params,
                self.weights,
                jnp.asarray(push),
                jnp.int32(shift),
            )
            self.recorder.end("comm")
            # a round touches its pushers and their ring targets; everyone
            # else ages — the per-worker staleness the detector watches
            pushers = np.flatnonzero(push > 0)
            self._last_touch[pushers] = self.iteration
            self._last_touch[(pushers + shift) % n] = self.iteration
        if self.telemetry is not None:
            if not dropped:
                # gossip_merge ppermutes the full fp32 float-param set of
                # ONE worker on every device for each of the `shift` hops
                # (the push mask zeroes values, not traffic), so the
                # round's per-device wire bytes are statically
                # shift * tree_bytes; step index is pre-increment,
                # matching the train.step span (see EASGD)
                self.telemetry.count(
                    _WIRE_BYTES, shift * self._gossip_hop_bytes(),
                    emit=True, step=self.iteration - 1, shift=int(shift))
            staleness = self.iteration - self._last_touch
            self.telemetry.instant(
                _ROUND_INSTANT, step=self.iteration - 1,
                staleness=int(staleness.max()),
                expected=round(1.0 / self.p_push, 3),
                shift=int(shift), dropped=bool(dropped))
            self.telemetry.metrics.gauge(_STALE_MAX_GAUGE,
                                         int(staleness.max()))
            self.telemetry.metrics.gauge(_STALE_MEAN_GAUGE,
                                         float(staleness.mean()))

    def warmup_exchange(self) -> None:
        if self.n_workers == 1:
            return
        # all-zero push: executes the compiled gossip round as a no-op merge
        # (shift is traced, so this one call covers every future draw)
        self.params, self.weights = self._gossip_fn(
            self.params, self.weights,
            jnp.zeros((self.n_workers,), jnp.float32), jnp.int32(1),
        )

    def eval_args(self):
        """Validate with the weighted consensus of all workers."""
        return self._consensus_fn(self.params, self.weights, self.state)

    def checkpoint_trees(self) -> dict:
        return {**super().checkpoint_trees(), "weights": self.weights}

    def _fingerprint_extra(self) -> dict:
        """ISSUE 20 rule-typed manifest stamp (see EASGD's for the layout
        tag / configured-value rationale; the gossip shift needs no stamp
        — it is a pure function of (seed, iteration), both already in the
        manifest)."""
        return {
            "rule": "gosgd",
            "p_push": ("auto" if self._p_push_cfg is None
                       else float(self._p_push_cfg)),
        }


class GOSGD(Rule):
    """Gossip rule.  Config: ``p_push``."""

    def make_trainer(self, model, mesh, recorder) -> GOSGDTrainer:
        return GOSGDTrainer(
            model,
            mesh=mesh,
            p_push=self.config.get("p_push"),
            **self.common_trainer_kwargs(recorder),
        )
