"""GOSGD: gossip data parallelism (reference's peer-to-peer async rule).

Reference (unverified — SURVEY.md §2.1/§3.4): ``gosgd_worker.py`` — after each
local step every worker draws Bernoulli(p); on success it sends half its
consensus weight ``w_i`` plus its parameters to a uniformly random peer, which
merges ``p_j ← (w_j·p_j + w_i/2·p_i)/(w_j + w_i/2)`` (Blot et al. 2016,
"Gossip training for deep learning").

TPU-native re-expression: the Bernoulli push draws and a random ring shift
``k ∈ {1..n-1}`` are sampled **on host** each round, then one compiled
collective round applies every push at once: pusher ``i``'s target is
``(i+k) mod n`` — marginally uniform over its peers, identical to the
reference's per-worker marginal — and the routing is ``k`` repetitions of the
single-hop ring ``ppermute`` (a ``fori_loop`` with a traced trip count), so a
round costs at most ``n-1`` ICI hops and needs no data-dependent permutation.

Routing-cost tradeoff (deliberate): a shift of ``k`` moves the whole
parameter tree ``k`` sequential hops — O(n) ICI latency worst-case.  The
alternative, one compiled program per shift (each a single direct
``ppermute`` by ``k``), costs one hop per round but ``n-1`` compiled
variants (compile time and HBM for executables scale with n) and loses the
single-trace property.  At gossip's design point — exchanges are rare
(``p_push ~ 1/n``) and overlap compute — hop latency is not the bottleneck,
so one traced program wins; revisit only if profiles show gossip rounds on
the critical path at pod scale.  Weight conservation (Σw = 1) holds by
construction.  Semantics changed:
pushes land at round boundaries instead of asynchronously mid-step, and
within one round targets are a cyclic shift (no collisions) rather than
jointly-iid — the per-worker target distribution is unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.parallel.mesh import DATA_AXIS, shard_map
from theanompi_tpu.parallel.trainer import (
    BaseTrainer,
    Rule,
    make_local_eval,
    make_local_step,
    require_data_parallel_mesh,
    pmean_floats,
    restack,
    stack_for_workers,
    unstack,
)


def gossip_merge(params, weight, push, shift, n, axis_name=DATA_AXIS):
    """One gossip round for this worker (pure, inside shard_map).

    ``params``: this worker's pytree; ``weight``: scalar consensus weight;
    ``push``: replicated 0/1 vector ``[n]`` of who pushes; ``shift``: traced
    ring shift — pusher ``i`` targets ``(i+shift) mod n``.  Returns merged
    (params, weight).
    """
    me = lax.axis_index(axis_name)
    my_push = push[me]
    sent_w = my_push * weight * 0.5
    kept_w = weight - sent_w

    is_float = lambda x: jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
    outgoing = [sent_w] + [
        sent_w * leaf.astype(jnp.float32)
        for leaf in jax.tree.leaves(params)
        if is_float(leaf)
    ]
    ring = [(i, (i + 1) % n) for i in range(n)]

    def hop(_, carry):
        return [lax.ppermute(x, axis_name, ring) for x in carry]

    # shift hops of the one-step ring == ppermute by the random shift; the
    # trip count is traced, so one compiled program serves every draw
    incoming = lax.fori_loop(0, shift, hop, outgoing)
    recv_w, recv_leaves = incoming[0], incoming[1:]
    new_w = kept_w + recv_w  # > 0 always: kept_w >= weight/2 > 0

    recv_iter = iter(recv_leaves)

    def merge(leaf):
        if not is_float(leaf):
            return leaf
        merged = (kept_w * leaf.astype(jnp.float32) + next(recv_iter)) / new_w
        return merged.astype(leaf.dtype)

    return jax.tree.map(merge, params), new_w


class GOSGDTrainer(BaseTrainer):
    """Local SGD + host-drawn randomized gossip rounds.

    ``p_push`` is the per-iteration Bernoulli probability (reference default
    semantics; 1/n keeps expected traffic at one push per round).
    """

    def __init__(self, model, mesh=None, p_push: float | None = None, **kwargs):
        super().__init__(model, mesh=mesh, **kwargs)
        require_data_parallel_mesh(self.mesh, "GOSGDTrainer")
        self.p_push = p_push if p_push is not None else 1.0 / max(self.n_workers, 2)
        self.weights = None
        self._gossip_fn = None
        self._consensus_fn = None
        # seeded in init_state so warmup()'s reset restores the full
        # deterministic schedule (push draws + ring shifts), not just params
        self._host_rng = None
        self._hop_bytes: int | None = None

    def _gossip_hop_bytes(self) -> int:
        """Per-device fp32 bytes one gossip hop moves: the float leaves of
        ONE worker's params (the stacked tree's leading axis is the worker
        count) plus the scalar consensus weight, all cast to fp32 on the
        wire by gossip_merge."""
        if self._hop_bytes is None:
            total = 4  # the ppermuted consensus-weight scalar
            for leaf in jax.tree.leaves(self.params):
                if jnp.issubdtype(leaf.dtype, jnp.inexact):
                    total += leaf.size // self.n_workers * 4
            self._hop_bytes = total
        return self._hop_bytes

    def compile_iter_fns(self) -> None:
        local_step = make_local_step(
            self.model, self.optimizer, jax.random.PRNGKey(self.seed),
            stacked=True,
            # per-worker guard, same reasoning as EASGD (params diverge by
            # design, so a per-worker skip cannot desynchronize anything)
            sentinel_skip=(self.sentinel is not None
                           and self.sentinel.device_guard),
        )
        local_eval = make_local_eval(self.model)
        n = self.n_workers

        def gossip(params, weight, push, shift):
            new_p, new_w = gossip_merge(
                unstack(params), unstack(weight), push, shift, n
            )
            return restack(new_p), new_w[None]

        def consensus(params, weight, state):
            params, state = unstack(params), unstack(state)
            w = unstack(weight)

            def avg(leaf):
                if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                    return leaf
                return lax.psum(w * leaf.astype(jnp.float32), DATA_AXIS).astype(
                    leaf.dtype
                )

            return jax.tree.map(avg, params), pmean_floats(state, DATA_AXIS)

        W = P(DATA_AXIS)
        self._step_fn = jax.jit(
            shard_map(
                local_step,
                self.mesh,
                in_specs=(W, W, W, W, P(), P()),
                out_specs=(W, W, W, W),
            ),
            donate_argnums=(0, 1, 2),
        )
        self._gossip_fn = jax.jit(
            shard_map(
                gossip, self.mesh, in_specs=(W, W, P(), P()), out_specs=(W, W)
            ),
            donate_argnums=(0, 1),
        )
        self._eval_fn = jax.jit(
            shard_map(local_eval, self.mesh, in_specs=(P(), P(), W), out_specs=P())
        )
        self._consensus_fn = jax.jit(
            shard_map(
                consensus, self.mesh, in_specs=(W, W, W), out_specs=(P(), P())
            )
        )

    def init_state(self) -> None:
        params, state = self.model.init_params(jax.random.PRNGKey(self.seed + 1))
        n = self.n_workers
        self.params = stack_for_workers(self.mesh, params, n)
        self.state = stack_for_workers(self.mesh, state, n)
        self.opt_state = stack_for_workers(self.mesh, self.model.init_opt_state(self.optimizer, params), n)
        self.weights = jax.device_put(
            np.full((n,), 1.0 / n, np.float32), NamedSharding(self.mesh, P(DATA_AXIS))
        )
        self._host_rng = np.random.RandomState(self.seed + 17)

    def post_step(self) -> None:
        n = self.n_workers
        if n == 1:
            return
        push = (self._host_rng.rand(n) < self.p_push).astype(np.float32)
        if not push.any():
            return  # no sender drawn this round — skip the collective
        # random ring shift: every pusher's target is uniform over its peers
        shift = self._host_rng.randint(1, n)
        self.recorder.start("comm")
        self.params, self.weights = self._gossip_fn(
            self.params,
            self.weights,
            jnp.asarray(push),
            jnp.int32(shift),
        )
        self.recorder.end("comm")
        if self.telemetry is not None:
            # gossip_merge ppermutes the full fp32 float-param set of ONE
            # worker on every device for each of the `shift` hops (the push
            # mask zeroes values, not traffic), so the round's per-device
            # wire bytes are statically shift * tree_bytes; step index is
            # pre-increment, matching the train.step span (see EASGD)
            self.telemetry.count(
                "exchange.wire_bytes", shift * self._gossip_hop_bytes(),
                emit=True, step=self.iteration - 1, shift=int(shift))

    def warmup_exchange(self) -> None:
        if self.n_workers == 1:
            return
        # all-zero push: executes the compiled gossip round as a no-op merge
        # (shift is traced, so this one call covers every future draw)
        self.params, self.weights = self._gossip_fn(
            self.params, self.weights,
            jnp.zeros((self.n_workers,), jnp.float32), jnp.int32(1),
        )

    def eval_args(self):
        """Validate with the weighted consensus of all workers."""
        return self._consensus_fn(self.params, self.weights, self.state)

    def checkpoint_trees(self) -> dict:
        return {**super().checkpoint_trees(), "weights": self.weights}


class GOSGD(Rule):
    """Gossip rule.  Config: ``p_push``."""

    def make_trainer(self, model, mesh, recorder) -> GOSGDTrainer:
        return GOSGDTrainer(
            model,
            mesh=mesh,
            p_push=self.config.get("p_push"),
            **self.common_trainer_kwargs(recorder),
        )
