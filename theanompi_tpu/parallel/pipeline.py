"""Pipeline parallelism: SPMD GPipe over the ``pipe`` mesh axis.

Beyond the reference's capability set (SURVEY.md §2 — 2016 data parallelism
only), but part of this framework's scale contract alongside tensor and
sequence parallelism.  The design is the collective-permute schedule every
TPU pipeline uses (the stacked-homogeneous-stages form):

- The model's repeated blocks are *stacked*: every block-param leaf carries
  a leading ``[n_stages, blocks_per_stage, ...]`` axis sharded over
  ``pipe``, so inside ``shard_map`` each device holds its own stage chunk
  and the SAME traced program runs on every stage (SPMD — no per-stage
  programs to compile).
- Each schedule step, every device applies its stage to the activation it
  holds, then the activations rotate one hop along the pipe ring
  (``ppermute``).  Stage 0 injects a fresh microbatch per step; the last
  stage's outputs accumulate into the output buffer.  ``n_micro + n_stages
  - 1`` steps drain the pipeline (the classic bubble).

Gradient correctness across the pipe axis uses the same pinned-VJP
collectives as tensor parallelism (``parallel/tensor.py``): the input is
wrapped in Megatron-``f`` over ``pipe`` (identity forward, psum backward)
because only stage 0's injection path carries the embedding cotangent, and
the output is replicated with Megatron-``g`` (psum forward, identity
backward) because only the last stage holds real outputs.  Params that are
NOT pipe-sharded (embeddings, the LM head) therefore get identical
gradients on every pipe rank, exactly like replicated params under tensor
parallelism.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.parallel.mesh import PIPE_AXIS
from theanompi_tpu.parallel.tensor import (
    axis_bound,
    identity_fwd_psum_bwd,
    psum_fwd_identity_bwd,
)


def pipeline_apply(stage_fn, stage_params, x, n_micro: int,
                   axis_name: str = PIPE_AXIS):
    """Run ``x`` through the pipelined stages; -> last-stage outputs.

    ``stage_fn(stage_params, act, t) -> act``: applies THIS device's stage
    chunk (``t`` is the schedule step, for rng folding).  ``stage_params``:
    the local chunk (leading stage axis of size 1 already squeezed by the
    caller).  ``x``: [B, ...] activations, replicated across ``pipe``
    (batch sharding over ``data`` is orthogonal).  ``n_micro`` must divide
    B.  Outside shard_map (or pipe size 1) this degrades to a plain call.
    """
    if not axis_bound(axis_name) or lax.axis_size(axis_name) == 1:
        return stage_fn(stage_params, x, 0)
    n_stages = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
    mb = b // n_micro
    xm = identity_fwd_psum_bwd(x, axis_name).reshape(n_micro, mb, *x.shape[1:])
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    steps = n_micro + n_stages - 1

    def body(carry, t):
        act, outbuf = carry
        # stage 0 injects microbatch t (clip: once drained it recomputes the
        # last one — the result never reaches the last stage before the
        # schedule ends, so it is dead work, not wrong work)
        inject = xm[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(me == 0, inject, act)
        y = stage_fn(stage_params, x_in, t)
        # the microbatch index this stage processed at step t
        m = t - me
        valid = jnp.logical_and(m >= 0, m < n_micro)
        is_last = me == n_stages - 1
        contrib = jnp.where(
            jnp.logical_and(valid, is_last), y, jnp.zeros_like(y)
        )
        # each (m) is written by exactly one (t, last-stage) pair; all other
        # adds are zeros, so accumulate-add is exact
        outbuf = outbuf.at[jnp.clip(m, 0, n_micro - 1)].add(
            contrib.astype(outbuf.dtype))
        act_next = lax.ppermute(y, axis_name, ring)
        return (act_next, outbuf), None

    act0 = jnp.zeros((mb, *x.shape[1:]), x.dtype)
    out0 = jnp.zeros(xm.shape, jnp.float32)
    (_, outbuf), _ = lax.scan(body, (act0, out0), jnp.arange(steps))
    outs = outbuf.reshape(b, *x.shape[1:]).astype(x.dtype)
    # replicate the last stage's outputs to every pipe rank (zeros
    # elsewhere); pinned backward: the replicated cotangent flows once into
    # each rank's contrib path, where the valid/is_last select routes it
    return psum_fwd_identity_bwd(outs, axis_name)
