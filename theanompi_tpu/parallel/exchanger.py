"""Strategy-pluggable parameter/gradient exchanger — the heart of the rebuild.

Reference (unverified — SURVEY.md §2.1): ``theanompi/lib/exchanger.py``
(``BSP_Exchanger.exchange()`` summing worker grads/params each iteration) and
``theanompi/lib/exchanger_strategy.py`` with config-string-selected collective
implementations:

====================  =============================================  =====================
reference strategy    what it did (GPU/MPI era)                      TPU-native analogue
====================  =============================================  =====================
``ar``                CUDA-aware ``MPI.Allreduce`` on gpuarray bufs  ``psum``
``nccl32``            pygpu/NCCL ``all_reduce`` fp32                 ``psum``
``asa16``/``nccl16``  fp16-compressed exchange                       ``psum_bf16``
``asa32``             alltoall-sum-allgather ring                    ``ring``
``copper``/``16``     host-staged copy path                          ``ring_bf16``
====================  =============================================  =====================

Every strategy here is a *pure function applied inside ``shard_map``* over the
``data`` mesh axis; XLA lowers ``psum``/``ppermute`` to ICI collectives, so
the "CUDA-aware" zero-copy property of the reference is automatic.  The
``ring*`` strategies are the explicit reduce-scatter/all-gather formulation
(the shape of the reference's ``asa`` strategies) built from ``ppermute`` —
mostly valuable as the template for custom collective schedules (and reused by
ring attention), since XLA's own ``psum`` lowering is already ring-based.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.parallel.mesh import DATA_AXIS

# strategy name -> fn(x, axis_name, axis_size) -> mean-reduced x
STRATEGIES: dict[str, Callable] = {}

#: strategies that put float leaves on the wire in bf16 (2 bytes/elem)
_BF16_WIRE = ("psum_bf16", "ring_bf16")


def wire_itemsize(strategy: str, dtype) -> int:
    """Bytes per element a leaf of ``dtype`` occupies on the ICI wire.

    The telemetry layer cannot observe the collective (it is fused into one
    XLA program), so bytes are accounted *statically* from the strategy's
    wire dtype: the bf16 strategies compress floating leaves to 2 bytes;
    everything else ships the leaf dtype verbatim; ``none`` ships nothing.
    """
    if strategy == "none":
        return 0
    itemsize = jnp.dtype(dtype).itemsize
    if strategy in _BF16_WIRE and jnp.issubdtype(dtype, jnp.floating):
        return min(itemsize, 2)
    return itemsize


def collective_wire_bytes(buffer_bytes: int, axis_size: int) -> int:
    """Per-device bytes on the wire for one all-reduce of ``buffer_bytes``.

    Ring all-reduce (reduce-scatter + all-gather — both the explicit
    ``ring*`` strategies and XLA's own ``psum`` lowering) moves
    ``2*(n-1)/n`` of the buffer through each device; n=1 moves nothing.
    """
    if axis_size <= 1:
        return 0
    return int(2 * (axis_size - 1) * buffer_bytes // axis_size)


def register_strategy(name: str):
    def deco(fn):
        STRATEGIES[name] = fn
        return fn

    return deco


@register_strategy("none")
def _no_exchange(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """No-op strategy: skip the collective entirely.

    Replicas diverge — NOT for training.  Exists for the scaling harness's
    differential comm measurement (step time with vs. without the exchange
    is the honest comm-share proxy when the collective is fused into one
    XLA program and invisible to host-side timers).
    """
    return x


@register_strategy("psum")
def _psum_mean(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Plain all-reduce mean (reference ``ar``/``nccl32``)."""
    return lax.psum(x, axis_name) / axis_size


@register_strategy("psum_bf16")
def _psum_bf16_mean(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """bf16-compressed all-reduce (reference ``asa16``/``nccl16``).

    Halves ICI bytes.  Note the accumulation itself is bf16 (XLA reduces in
    the wire dtype), so rounding error grows ~O(n) with worker count exactly
    as the reference's fp16 strategies' did; only the final mean division is
    fp32.  Use plain ``psum`` when numerics matter more than bandwidth.
    """
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return _psum_mean(x, axis_name, axis_size)
    summed = lax.psum(x.astype(jnp.bfloat16), axis_name)
    return (summed.astype(jnp.float32) / axis_size).astype(x.dtype)


def _ring_allreduce(x: jax.Array, axis_name: str, n: int, wire_dtype=None) -> jax.Array:
    """Explicit ring all-reduce: reduce-scatter then all-gather via ppermute.

    Equivalent communication shape to the reference's ``asa32``/``asa16``
    (alltoall-sum-allgather) strategies.  2*(n-1) ppermute steps, each moving
    1/n of the buffer around the ring.
    """
    if n == 1:
        return x
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(n, -1)
    if wire_dtype is not None and jnp.issubdtype(orig_dtype, jnp.floating):
        chunks = chunks.astype(wire_dtype)
    idx = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % n) for i in range(n)]

    # Reduce-scatter: after step s, device i holds the partial sum of chunk
    # (i - s - 1) mod n over s+2 contributors; after n-1 steps, device i owns
    # the complete chunk (i + 1) mod n.
    for s in range(n - 1):
        send = jnp.take(chunks, (idx - s) % n, axis=0)
        recv = lax.ppermute(send, axis_name, ring)
        tgt = (idx - s - 1) % n
        chunks = lax.dynamic_update_index_in_dim(
            chunks, lax.dynamic_index_in_dim(chunks, tgt, 0, keepdims=False) + recv,
            tgt, 0,
        )
    # All-gather: circulate the completed chunks.
    for s in range(n - 1):
        send = jnp.take(chunks, (idx + 1 - s) % n, axis=0)
        recv = lax.ppermute(send, axis_name, ring)
        chunks = lax.dynamic_update_index_in_dim(chunks, recv, (idx - s) % n, 0)

    out = chunks.astype(jnp.float32) if wire_dtype is not None else chunks
    out = out.reshape(-1)[: flat.size - pad if pad else flat.size]
    return out.reshape(orig_shape).astype(orig_dtype)


@register_strategy("ring")
def _ring_mean(x, axis_name, axis_size):
    return _ring_allreduce(x, axis_name, axis_size) / axis_size


@register_strategy("ring_bf16")
def _ring_bf16_mean(x, axis_name, axis_size):
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return _ring_mean(x, axis_name, axis_size)
    out = _ring_allreduce(x, axis_name, axis_size, wire_dtype=jnp.bfloat16)
    return (out.astype(jnp.float32) / axis_size).astype(x.dtype)


class Exchanger:
    """Averages a gradient/parameter pytree across the ``data`` axis.

    Reference: ``BSP_Exchanger`` (SURVEY.md §2.1) — there, a post-step host
    call dispatching to MPI/NCCL; here, a pure pytree transform invoked
    *inside* the compiled train step, so XLA overlaps the collective with
    remaining compute where the dependence structure allows.

    ``strategy`` is the plug point, preserved from the reference's
    config-string mechanism: one of ``STRATEGIES`` keys.  The axis size is
    derived *inside* the mapped context (``lax.axis_size``), so it can never
    disagree with the actual mesh.
    """

    def __init__(self, strategy: str = "psum",
                 axis_name: str | tuple[str, ...] = DATA_AXIS):
        if strategy not in STRATEGIES:
            raise ValueError(
                f"unknown exchange strategy {strategy!r}; "
                f"available: {sorted(STRATEGIES)}"
            )
        if isinstance(axis_name, (tuple, list)) and len(axis_name) > 1:
            if strategy not in ("psum", "psum_bf16", "none"):
                raise ValueError(
                    f"strategy {strategy!r} reduces over a single ring; "
                    f"multi-axis exchange ({axis_name}) needs 'psum'/'psum_bf16'"
                )
            axis_name = tuple(axis_name)
        elif isinstance(axis_name, (tuple, list)):
            axis_name = axis_name[0]
        self.strategy = strategy
        self.axis_name = axis_name
        self._fn = STRATEGIES[strategy]

    def exchange(self, tree):
        """Mean-reduce every floating leaf across the exchange axes.

        Call inside ``shard_map`` over a mesh that binds ``axis_name``
        (a single axis, or a tuple — e.g. ``("data", "seq")`` when gradients
        carry per-sequence-shard partial contributions too).
        Non-float leaves (step counters and other bookkeeping that may ride
        along in an optimizer-state pytree) pass through unchanged —
        mean-reducing them would silently promote ints to floats.
        """
        axes = (
            self.axis_name
            if isinstance(self.axis_name, tuple)
            else (self.axis_name,)
        )
        try:
            n = 1
            for a in axes:
                n *= lax.axis_size(a)
        except NameError as e:
            raise ValueError(
                f"Exchanger.exchange must run inside shard_map over a mesh "
                f"binding axes {axes!r}"
            ) from e
        if n == 1:
            return tree

        def reduce_leaf(x):
            if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
                return x
            return self._fn(x, axis_name=self.axis_name, axis_size=n)

        return jax.tree.map(reduce_leaf, tree)

    def wire_bytes(self, tree, axis_size: int) -> int:
        """Static per-device bytes-on-wire for ONE exchange of ``tree``.

        Counts exactly the leaves :meth:`exchange` reduces (inexact dtypes
        only) at the strategy's wire dtype, times the ring traffic factor —
        the telemetry layer's collective accounting (ISSUE 1): ``psum`` at
        fp32 reports EXACTLY 2x the bytes of ``psum_bf16`` for the same
        tree (the ring factor floors the per-leaf *element* count, then
        multiplies by the wire itemsize, so compression scales the result
        linearly).  ``tree`` may hold arrays or ``ShapeDtypeStruct``s.
        """
        if axis_size <= 1:
            return 0
        total = 0
        for leaf in jax.tree.leaves(tree):
            dtype = jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") \
                else leaf.dtype
            if not jnp.issubdtype(dtype, jnp.inexact):
                continue
            size = 1
            for d in getattr(leaf, "shape", ()):
                size *= int(d)
            wire_elems = 2 * (axis_size - 1) * size // axis_size
            total += wire_elems * wire_itemsize(self.strategy, dtype)
        return total

    def __repr__(self):
        return f"Exchanger(strategy={self.strategy!r}, axis={self.axis_name!r})"
