"""Strategy-pluggable parameter/gradient exchanger — the heart of the rebuild.

Reference (unverified — SURVEY.md §2.1): ``theanompi/lib/exchanger.py``
(``BSP_Exchanger.exchange()`` summing worker grads/params each iteration) and
``theanompi/lib/exchanger_strategy.py`` with config-string-selected collective
implementations:

====================  =============================================  =====================
reference strategy    what it did (GPU/MPI era)                      TPU-native analogue
====================  =============================================  =====================
``ar``                CUDA-aware ``MPI.Allreduce`` on gpuarray bufs  ``psum``
``nccl32``            pygpu/NCCL ``all_reduce`` fp32                 ``psum``
``asa16``/``nccl16``  fp16-compressed exchange                       ``psum_bf16``
``asa32``             alltoall-sum-allgather ring                    ``ring``
``copper``/``16``     host-staged copy path                          ``ring_bf16``
====================  =============================================  =====================

Every strategy here is a *pure function applied inside ``shard_map``* over the
``data`` mesh axis; XLA lowers ``psum``/``ppermute`` to ICI collectives, so
the "CUDA-aware" zero-copy property of the reference is automatic.  The
``ring*`` strategies are the explicit reduce-scatter/all-gather formulation
(the shape of the reference's ``asa`` strategies) built from ``ppermute`` —
mostly valuable as the template for custom collective schedules (and reused by
ring attention), since XLA's own ``psum`` lowering is already ring-based.

Bucketed exchange (ISSUE 2)
---------------------------

The leaf-wise strategies above issue ONE collective per parameter tensor —
dozens per step for ResNet-50/transformer_lm, each paying per-message launch
latency.  The ``*_bucket`` strategies (plus ``ring_int8`` and ``zero1``,
which are bucket-native) instead flatten the floating leaves and pack them
into a small number of fixed-size fused buckets (default ~4 MiB, leaves
grouped by dtype, greedy fill — an oversized leaf gets its own bucket) before
the collective and unpack after, so a 100+-leaf model compiles to a handful
of ``all-reduce`` HLO ops (lint-tested in ``tests/test_lint_collectives.py``).
With ``overlap=True`` (the ``exch_overlap`` rule key) the bucketed strategies
additionally chain the per-bucket collectives in reverse layout order so they
issue *during* backward instead of trailing it — mechanism, bit-equality
contract, and audit story in :mod:`theanompi_tpu.parallel.overlap`.

- ``psum_bucket``/``psum_bf16_bucket`` — fused-bucket analogues of
  ``psum``/``psum_bf16`` (multi-axis capable, like their leaf-wise twins).
- ``ring_bucket``/``ring_bf16_bucket`` — the explicit ppermute ring over
  fused buckets.
- ``ring_int8`` — int8-quantized ring (the modern analogue of the
  reference's compressed ``asa16`` path): each hop ships an int8 payload
  plus ONE fp32 per-chunk scale, with stochastic rounding so the
  quantization error is zero-mean.  Like the reference's fp16 strategies,
  accumulation error grows ~O(n) with worker count; the final all-gather
  circulates each owner's quantized bytes verbatim, so every replica
  dequantizes identical values (replicas cannot drift).
- ``zero1`` — ZeRO-1-style sharded update: each grad bucket is
  reduce-scattered (mean), the optimizer update runs on only the local 1/n
  shard of params + opt_state (see :func:`theanompi_tpu.ops.opt.sharded_update`),
  and updated params are all-gathered.  Optimizer-state HBM and update
  FLOPs drop by n; params stay replicated for eval/checkpoint.  Because
  the exchange and the update fuse, the trainer calls
  :meth:`Exchanger.exchange_and_update` instead of ``exchange`` (the
  ``fuses_update`` plug point).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.mesh import DATA_AXIS
from theanompi_tpu.parallel.overlap import fence as _fence
from theanompi_tpu.parallel.overlap import overlap_pred as _overlap_pred

# strategy name -> fn(x, axis_name, axis_size) -> mean-reduced x (leaf-wise)
STRATEGIES: dict[str, Callable] = {}

#: bucketed strategies — fused flat buckets instead of one collective/leaf
BUCKETED_STRATEGIES = (
    "psum_bucket",
    "psum_bf16_bucket",
    "ring_bucket",
    "ring_bf16_bucket",
    "ring_int8",
    "zero1",
)

#: strategies that may reduce over multiple mesh axes at once (plain psum
#: accepts an axis tuple; the ring/scatter schedules assume ONE ring)
_MULTI_AXIS_OK = ("psum", "psum_bf16", "none", "psum_bucket", "psum_bf16_bucket")

#: strategies that put float leaves on the wire in bf16 (2 bytes/elem)
_BF16_WIRE = ("psum_bf16", "ring_bf16", "psum_bf16_bucket", "ring_bf16_bucket")
#: strategies that put float leaves on the wire in int8 (1 byte/elem;
#: per-chunk fp32 scales excluded from accounting — see Exchanger.wire_bytes)
_INT8_WIRE = ("ring_int8",)

DEFAULT_BUCKET_BYTES = 4 * 2**20

#: fold_in tag callers use to derive the exchange rng stream (ring_int8
#: stochastic rounding) from their per-step key — distinct from dropout's
#: micro-batch folds, which use small ints
EXCHANGE_RNG_TAG = 0x45584348  # "EXCH"


def wire_itemsize(strategy: str, dtype) -> int:
    """Bytes per element a leaf of ``dtype`` occupies on the ICI wire.

    The telemetry layer cannot observe the collective (it is fused into one
    XLA program), so bytes are accounted *statically* from the strategy's
    wire dtype: the bf16 strategies compress floating leaves to 2 bytes and
    ``ring_int8`` to 1; everything else (including ``zero1``'s
    reduce-scatter + all-gather) ships the leaf dtype verbatim; ``none``
    ships nothing.
    """
    if strategy == "none":
        return 0
    itemsize = jnp.dtype(dtype).itemsize
    if jnp.issubdtype(dtype, jnp.floating):
        if strategy in _BF16_WIRE:
            return min(itemsize, 2)
        if strategy in _INT8_WIRE:
            return min(itemsize, 1)
    return itemsize


def collective_wire_bytes(buffer_bytes: int, axis_size: int) -> int:
    """Per-device bytes on the wire for one all-reduce of ``buffer_bytes``.

    Ring all-reduce (reduce-scatter + all-gather — both the explicit
    ``ring*`` strategies and XLA's own ``psum`` lowering) moves
    ``2*(n-1)/n`` of the buffer through each device; n=1 moves nothing.
    """
    if axis_size <= 1:
        return 0
    return int(2 * (axis_size - 1) * buffer_bytes // axis_size)


def register_strategy(name: str):
    def deco(fn):
        STRATEGIES[name] = fn
        return fn

    return deco


@register_strategy("none")
def _no_exchange(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """No-op strategy: skip the collective entirely.

    Replicas diverge — NOT for training.  Exists for the scaling harness's
    differential comm measurement (step time with vs. without the exchange
    is the honest comm-share proxy when the collective is fused into one
    XLA program and invisible to host-side timers).
    """
    return x


@register_strategy("psum")
def _psum_mean(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Plain all-reduce mean (reference ``ar``/``nccl32``)."""
    return lax.psum(x, axis_name) / axis_size


@register_strategy("psum_bf16")
def _psum_bf16_mean(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """bf16-compressed all-reduce (reference ``asa16``/``nccl16``).

    Halves ICI bytes.  Note the accumulation itself is bf16 (XLA reduces in
    the wire dtype), so rounding error grows ~O(n) with worker count exactly
    as the reference's fp16 strategies' did; only the final mean division is
    fp32.  Use plain ``psum`` when numerics matter more than bandwidth.
    """
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return _psum_mean(x, axis_name, axis_size)
    summed = lax.psum(x.astype(jnp.bfloat16), axis_name)
    return (summed.astype(jnp.float32) / axis_size).astype(x.dtype)


def _ring_allreduce(x: jax.Array, axis_name: str, n: int, wire_dtype=None) -> jax.Array:
    """Explicit ring all-reduce: reduce-scatter then all-gather via ppermute.

    Equivalent communication shape to the reference's ``asa32``/``asa16``
    (alltoall-sum-allgather) strategies.  2*(n-1) ppermute steps, each moving
    1/n of the buffer around the ring.  Chunk selection uses
    ``lax.dynamic_index_in_dim`` (a 1/n slice), NOT ``jnp.take`` — take
    lowers to a gather over the whole chunk array per hop, touching n× the
    bytes each step actually needs.
    """
    if n == 1:
        return x
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(n, -1)
    if wire_dtype is not None and jnp.issubdtype(orig_dtype, jnp.floating):
        chunks = chunks.astype(wire_dtype)
    idx = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % n) for i in range(n)]

    # Reduce-scatter: after step s, device i holds the partial sum of chunk
    # (i - s - 1) mod n over s+2 contributors; after n-1 steps, device i owns
    # the complete chunk (i + 1) mod n.
    for s in range(n - 1):
        send = lax.dynamic_index_in_dim(chunks, (idx - s) % n, 0, keepdims=False)
        recv = lax.ppermute(send, axis_name, ring)
        tgt = (idx - s - 1) % n
        chunks = lax.dynamic_update_index_in_dim(
            chunks, lax.dynamic_index_in_dim(chunks, tgt, 0, keepdims=False) + recv,
            tgt, 0,
        )
    # All-gather: circulate the completed chunks.
    for s in range(n - 1):
        send = lax.dynamic_index_in_dim(chunks, (idx + 1 - s) % n, 0,
                                        keepdims=False)
        recv = lax.ppermute(send, axis_name, ring)
        chunks = lax.dynamic_update_index_in_dim(chunks, recv, (idx - s) % n, 0)

    out = chunks.astype(jnp.float32) if wire_dtype is not None else chunks
    out = out.reshape(-1)[: flat.size - pad if pad else flat.size]
    return out.reshape(orig_shape).astype(orig_dtype)


@register_strategy("ring")
def _ring_mean(x, axis_name, axis_size):
    return _ring_allreduce(x, axis_name, axis_size) / axis_size


@register_strategy("ring_bf16")
def _ring_bf16_mean(x, axis_name, axis_size):
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return _ring_mean(x, axis_name, axis_size)
    out = _ring_allreduce(x, axis_name, axis_size, wire_dtype=jnp.bfloat16)
    return (out.astype(jnp.float32) / axis_size).astype(x.dtype)


# -- int8 quantized ring (the modern ``asa16``) ------------------------------
# The per-chunk-scale + stochastic-rounding primitive moved to
# ``ops/quant.py`` (ISSUE 6) so the serving path's weight quantization can
# share the exact wire format without importing this training-side module.
from theanompi_tpu.ops.quant import quantize_chunk as _quantize_chunk  # noqa: E402


def _ring_allreduce_int8(x: jax.Array, axis_name: str, n: int,
                         key: jax.Array) -> jax.Array:
    """Ring all-reduce with int8 + per-chunk-scale wire format (fp32 math).

    Reduce-scatter: each hop quantizes the outgoing fp32 partial sum,
    ships (int8, scale), and the receiver dequantizes into its fp32
    accumulator.  All-gather: each completed chunk is quantized ONCE by
    its owner and the payload circulates verbatim, so every replica
    dequantizes bit-identical values — replicas cannot drift.  Returns
    fp32 (callers divide by n and cast back).
    """
    if n == 1:
        return x.astype(jnp.float32)
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(n, -1)
    idx = lax.axis_index(axis_name)
    ring = [(i, (i + 1) % n) for i in range(n)]

    for s in range(n - 1):
        send = lax.dynamic_index_in_dim(chunks, (idx - s) % n, 0, keepdims=False)
        q, scale = _quantize_chunk(send, jax.random.fold_in(key, s))
        recv = (lax.ppermute(q, axis_name, ring).astype(jnp.float32)
                * lax.ppermute(scale, axis_name, ring))
        tgt = (idx - s - 1) % n
        chunks = lax.dynamic_update_index_in_dim(
            chunks, lax.dynamic_index_in_dim(chunks, tgt, 0, keepdims=False) + recv,
            tgt, 0,
        )
    own = lax.dynamic_index_in_dim(chunks, (idx + 1) % n, 0, keepdims=False)
    q_own, s_own = _quantize_chunk(own, jax.random.fold_in(key, n - 1))
    qc = lax.dynamic_update_index_in_dim(
        jnp.zeros(chunks.shape, jnp.int8), q_own, (idx + 1) % n, 0)
    sc = lax.dynamic_update_index_in_dim(
        jnp.zeros((n,), jnp.float32), s_own, (idx + 1) % n, 0)
    for s in range(n - 1):
        send_q = lax.dynamic_index_in_dim(qc, (idx + 1 - s) % n, 0,
                                          keepdims=False)
        send_s = lax.dynamic_index_in_dim(sc, (idx + 1 - s) % n, 0,
                                          keepdims=False)
        qc = lax.dynamic_update_index_in_dim(
            qc, lax.ppermute(send_q, axis_name, ring), (idx - s) % n, 0)
        sc = lax.dynamic_update_index_in_dim(
            sc, lax.ppermute(send_s, axis_name, ring), (idx - s) % n, 0)
    out = qc.astype(jnp.float32) * sc[:, None]
    out = out.reshape(-1)[: flat.size - pad if pad else flat.size]
    return out.reshape(x.shape)


# -- bucket layout -----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _Bucket:
    """One fused flat buffer: which leaves it packs and where."""

    dtype: object
    indices: tuple[int, ...]   # flat-leaf indices packed, in order
    sizes: tuple[int, ...]     # element count per packed leaf
    shapes: tuple[tuple, ...]  # original shape per packed leaf
    elems: int                 # payload elements (sum of sizes)
    padded: int                # elems rounded up to a multiple of n


def _leaf_meta(leaf):
    """(shape, dtype) for arrays, ShapeDtypeStructs, and bare scalars."""
    if hasattr(leaf, "dtype"):
        return tuple(getattr(leaf, "shape", ())), jnp.dtype(leaf.dtype)
    arr = jnp.asarray(leaf)
    return tuple(arr.shape), jnp.dtype(arr.dtype)


def _bucket_layout(leaves, bucket_bytes: int, n: int) -> list[_Bucket]:
    """Greedy dtype-grouped fused buckets over the inexact leaves.

    Deterministic in the leaf order, so the layout computed at trace time
    (inside ``shard_map``) and host-side (opt-state init, wire accounting)
    always agrees.  Leaves are never split: one larger than ``bucket_bytes``
    simply gets its own (oversized) bucket.  Each bucket is padded to a
    multiple of ``n`` so ring chunking and reduce-scatter divide evenly.
    """
    groups: dict = {}
    for i, leaf in enumerate(leaves):
        shape, dtype = _leaf_meta(leaf)
        if not jnp.issubdtype(dtype, jnp.inexact):
            continue
        groups.setdefault(dtype, []).append((i, shape, math.prod(shape)))
    buckets: list[_Bucket] = []
    for dtype, entries in groups.items():
        cap = max(1, int(bucket_bytes) // max(1, jnp.dtype(dtype).itemsize))
        cur: list = []
        cur_elems = 0
        for i, shape, size in entries:
            if cur and cur_elems + size > cap:
                buckets.append(_make_bucket(dtype, cur, cur_elems, n))
                cur, cur_elems = [], 0
            cur.append((i, shape, size))
            cur_elems += size
        if cur:
            buckets.append(_make_bucket(dtype, cur, cur_elems, n))
    return buckets


def _make_bucket(dtype, entries, elems, n) -> _Bucket:
    return _Bucket(
        dtype=dtype,
        indices=tuple(e[0] for e in entries),
        shapes=tuple(e[1] for e in entries),
        sizes=tuple(e[2] for e in entries),
        elems=elems,
        padded=elems + (-elems) % max(1, n),
    )


def _pack(leaves, bucket: _Bucket) -> jax.Array:
    parts = [jnp.asarray(leaves[i]).reshape(-1) for i in bucket.indices]
    if bucket.padded > bucket.elems:
        parts.append(jnp.zeros((bucket.padded - bucket.elems,), bucket.dtype))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def _unpack(buf: jax.Array, bucket: _Bucket) -> dict:
    """-> {flat-leaf index: reduced array} for the leaves ``bucket`` packs."""
    out, off = {}, 0
    for i, size, shape in zip(bucket.indices, bucket.sizes, bucket.shapes):
        out[i] = lax.slice(buf, (off,), (off + size,)).reshape(shape)
        off += size
    return out


def fused_pmean(tree, axis_name):
    """Mean-reduce every inexact leaf with ONE collective per dtype.

    The fused analogue of mapping ``lax.pmean`` leaf-by-leaf (trainer
    metrics / BN-state consensus): the same pack/unpack machinery as the
    bucketed exchange, with an unbounded bucket per dtype — a 16-leaf
    state tree costs one all-reduce instead of 16.  Non-float leaves
    (step counters) pass through unchanged.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = list(leaves)
    # one bucket per dtype, no padding (n=1), no size cap
    for bucket in _bucket_layout(leaves, bucket_bytes=2**62, n=1):
        red = lax.pmean(_pack(leaves, bucket), axis_name)
        for i, arr in _unpack(red, bucket).items():
            out[i] = arr
    return jax.tree_util.tree_unflatten(treedef, out)


class Exchanger:
    """Averages a gradient/parameter pytree across the ``data`` axis.

    Reference: ``BSP_Exchanger`` (SURVEY.md §2.1) — there, a post-step host
    call dispatching to MPI/NCCL; here, a pure pytree transform invoked
    *inside* the compiled train step, so XLA overlaps the collective with
    remaining compute where the dependence structure allows.

    ``strategy`` is the plug point, preserved from the reference's
    config-string mechanism: one of ``STRATEGIES`` keys (leaf-wise) or
    ``BUCKETED_STRATEGIES`` (fused flat buckets — see module docstring).
    ``bucket_bytes`` caps the fused-bucket payload (default 4 MiB).  The
    axis size is derived *inside* the mapped context (``lax.axis_size``),
    so it can never disagree with the actual mesh.

    ``zero1`` fuses the exchange into the optimizer update
    (``fuses_update``): the trainer calls :meth:`exchange_and_update`
    and stores the optimizer state in this exchanger's sharded bucket
    layout (:meth:`zero1_init_opt_state` / :meth:`zero1_opt_state_specs`).
    """

    def __init__(self, strategy: str = "psum",
                 axis_name: str | tuple[str, ...] = DATA_AXIS,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 overlap: bool = False):
        known = set(STRATEGIES) | set(BUCKETED_STRATEGIES)
        if strategy not in known:
            raise ValueError(
                f"unknown exchange strategy {strategy!r}; "
                f"available: {sorted(known)}"
            )
        if isinstance(axis_name, (tuple, list)) and len(axis_name) > 1:
            if strategy not in _MULTI_AXIS_OK:
                raise ValueError(
                    f"strategy {strategy!r} reduces over a single ring; "
                    f"multi-axis exchange ({axis_name}) needs one of "
                    f"{sorted(_MULTI_AXIS_OK)}"
                )
            axis_name = tuple(axis_name)
        elif isinstance(axis_name, (tuple, list)):
            axis_name = axis_name[0]
        if int(bucket_bytes) < 1:
            raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
        if overlap and strategy not in BUCKETED_STRATEGIES:
            raise ValueError(
                f"exch_overlap chains per-bucket collectives; strategy "
                f"{strategy!r} is not bucketed (one of {BUCKETED_STRATEGIES})"
            )
        self.strategy = strategy
        self.axis_name = axis_name
        self.bucket_bytes = int(bucket_bytes)
        self.overlap = bool(overlap)
        self._fn = STRATEGIES.get(strategy)

    # -- properties ----------------------------------------------------------
    @property
    def bucketed(self) -> bool:
        return self.strategy in BUCKETED_STRATEGIES

    @property
    def fuses_update(self) -> bool:
        """True when the strategy fuses exchange + optimizer update (zero1):
        the trainer must call :meth:`exchange_and_update`, not ``exchange``."""
        return self.strategy == "zero1"

    # -- mapped-context helpers ----------------------------------------------
    def _axes(self) -> tuple:
        return (self.axis_name if isinstance(self.axis_name, tuple)
                else (self.axis_name,))

    def _mapped_axis_size(self) -> int:
        try:
            n = 1
            for a in self._axes():
                n *= lax.axis_size(a)
            return n
        except NameError as e:
            raise ValueError(
                f"Exchanger.exchange must run inside shard_map over a mesh "
                f"binding axes {self._axes()!r}"
            ) from e

    def _chain_pred(self, step):
        """The fence predicate for the overlap chain, from the traced step
        scalar — see :mod:`theanompi_tpu.parallel.overlap`."""
        if step is None:
            raise ValueError(
                "exch_overlap needs the traced int32 step scalar to anchor "
                "the fence chain; pass step= to exchange()/exchange_and_update()"
            )
        return _overlap_pred(step)

    # -- exchange ------------------------------------------------------------
    def exchange(self, tree, rng=None, step=None):
        """Mean-reduce every floating leaf across the exchange axes.

        Call inside ``shard_map`` over a mesh that binds ``axis_name``
        (a single axis, or a tuple — e.g. ``("data", "seq")`` when gradients
        carry per-sequence-shard partial contributions too).
        Non-float leaves (step counters and other bookkeeping that may ride
        along in an optimizer-state pytree) pass through unchanged —
        mean-reducing them would silently promote ints to floats.

        ``rng`` seeds ``ring_int8``'s stochastic rounding (ignored by every
        other strategy); pass a fresh per-step key so the rounding noise
        decorrelates across steps — ``None`` falls back to a fixed key.

        ``step`` (the traced int32 step scalar) is required when
        ``overlap`` is on: buckets are walked in reverse layout order and
        each bucket's buffer is fenced on the previous bucket's reduction
        (see :mod:`theanompi_tpu.parallel.overlap`), so collectives issue
        during backward instead of trailing it.  The per-bucket rng fold
        uses the bucket *index*, not the walk order, so ``ring_int8``'s
        rounding noise — and therefore the result — is identical to the
        fused walk.
        """
        if self.fuses_update:
            raise ValueError(
                "zero1 fuses the exchange into the optimizer update; "
                "call exchange_and_update(grads, opt_state, params, lr, opt)"
            )
        n = self._mapped_axis_size()
        if n == 1:
            return tree

        if not self.bucketed:
            def reduce_leaf(x):
                if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
                    return x
                return self._fn(x, axis_name=self.axis_name, axis_size=n)

            return jax.tree.map(reduce_leaf, tree)

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        out = list(leaves)
        buckets = _bucket_layout(leaves, self.bucket_bytes, n)
        order = range(len(buckets))
        pred, prev = None, None
        if self.overlap:
            pred = self._chain_pred(step)
            order = reversed(order)
        for bi in order:
            bucket = buckets[bi]
            key = None
            if self.strategy == "ring_int8":
                base = rng if rng is not None else jax.random.PRNGKey(0)
                key = jax.random.fold_in(base, bi)
            buf = _pack(leaves, bucket)
            if prev is not None:
                buf = _fence(buf, prev, pred)
            red = self._reduce_bucket(buf, n, key)
            if self.overlap:
                prev = red
            for i, arr in _unpack(red, bucket).items():
                out[i] = arr
        return jax.tree_util.tree_unflatten(treedef, out)

    def _reduce_bucket(self, buf: jax.Array, n: int, key) -> jax.Array:
        s = self.strategy
        if s == "psum_bucket":
            return lax.psum(buf, self.axis_name) / n
        if s == "psum_bf16_bucket":
            summed = lax.psum(buf.astype(jnp.bfloat16), self.axis_name)
            return (summed.astype(jnp.float32) / n).astype(buf.dtype)
        if s == "ring_bucket":
            return _ring_allreduce(buf, self.axis_name, n) / n
        if s == "ring_bf16_bucket":
            out = _ring_allreduce(buf, self.axis_name, n,
                                  wire_dtype=jnp.bfloat16)
            return (out.astype(jnp.float32) / n).astype(buf.dtype)
        if s == "ring_int8":
            out = _ring_allreduce_int8(buf, self.axis_name, n, key)
            return (out / n).astype(buf.dtype)
        raise AssertionError(f"not a bucketed reduce strategy: {s}")

    # -- zero1: fused exchange + sharded optimizer update --------------------
    def exchange_and_update(self, grads, opt_state, params, lr, opt, rng=None,
                            step=None):
        """ZeRO-1 step: reduce-scatter grad buckets (mean), update the local
        1/n shard of params with the (sharded) ``opt_state``, all-gather the
        updated params.  -> (new_params, new_opt_state).

        ``opt_state`` must be in this exchanger's bucket layout
        (:meth:`zero1_init_opt_state`), stored with
        :meth:`zero1_opt_state_specs` so each device holds exactly its
        shard.  Non-inexact param leaves pass through un-updated (same
        skip as ``exchange``; float params are the contract).  ``rng`` is
        accepted for signature parity with ``exchange`` and unused.

        With ``overlap`` on (``step`` required), all three stages are
        chained in reverse layout order — the backward-readiness order:
        each bucket's packed grads are fenced on the previous bucket's
        scatter result (reduce-scatters issue during backward), the
        shard-local updates consume scattered buckets as they arrive
        (``chain=`` on :func:`theanompi_tpu.ops.opt.sharded_update`), and
        each all-gather is fenced on the previous gather (gathers issue
        as their bucket's update lands).  All fences are value-preserving,
        so the result is bit-identical to the unfenced schedule.
        """
        from theanompi_tpu.ops.opt import sharded_update

        n = self._mapped_axis_size()
        axis = self.axis_name
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = jax.tree_util.tree_flatten(grads)[0]
        buckets = _bucket_layout(p_leaves, self.bucket_bytes, n)
        idx = lax.axis_index(axis) if n > 1 else 0
        overlap = self.overlap and n > 1
        order = list(range(len(buckets)))
        pred, chain = None, None
        if overlap:
            pred = self._chain_pred(step)
            order = order[::-1]
            chain = (order, lambda buf, prev: _fence(buf, prev, pred))
        g_shards: list = [None] * len(buckets)
        p_shards: list = [None] * len(buckets)
        prev = None
        for bi in order:
            bucket = buckets[bi]
            g = _pack(g_leaves, bucket)
            p = _pack(p_leaves, bucket)
            if n > 1:
                if prev is not None:
                    g = _fence(g, prev, pred)
                g = lax.psum_scatter(g.reshape(n, -1), axis,
                                     scatter_dimension=0, tiled=False) / n
                p = lax.dynamic_index_in_dim(p.reshape(n, -1), idx, 0,
                                             keepdims=False)
            if overlap:
                prev = g
            g_shards[bi] = g
            p_shards[bi] = p
        new_shards, new_opt_state = sharded_update(
            opt, g_shards, opt_state, p_shards, lr, axis_name=axis,
            chain=chain)
        out = list(p_leaves)
        prev = None
        for bi in order:
            bucket, shard = buckets[bi], new_shards[bi]
            if n > 1:
                if prev is not None:
                    shard = _fence(shard, prev, pred)
                full = lax.all_gather(shard, axis, axis=0, tiled=True)
            else:
                full = shard
            if overlap:
                prev = full
            for i, arr in _unpack(full, bucket).items():
                out[i] = arr
        return jax.tree_util.tree_unflatten(treedef, out), new_opt_state

    def zero1_layout(self, params, axis_size: int) -> list[_Bucket]:
        """The bucket layout for ``params`` at worker count ``axis_size`` —
        host-side twin of the trace-time layout (same greedy walk over the
        same leaf order, so they cannot disagree)."""
        leaves = jax.tree_util.tree_flatten(params)[0]
        return _bucket_layout(leaves, self.bucket_bytes, max(1, axis_size))

    def zero1_init_opt_state(self, optimizer, params, axis_size: int):
        """Optimizer state over flat GLOBAL ``(padded,)`` bucket buffers —
        place with :meth:`zero1_opt_state_specs` so each device stores only
        its ``1/n`` slice (the ZeRO-1 HBM saving)."""
        tmpl = [jnp.zeros((b.padded,), b.dtype)
                for b in self.zero1_layout(params, axis_size)]
        return optimizer.init(tmpl)

    def zero1_opt_state_specs(self, optimizer, params, axis_size: int):
        specs = [P(self.axis_name)
                 for _ in self.zero1_layout(params, axis_size)]
        return optimizer.init_specs(specs)

    # -- static accounting ---------------------------------------------------
    def wire_bytes(self, tree, axis_size: int) -> int:
        """Static per-device bytes-on-wire for ONE exchange of ``tree``.

        Counts exactly the payload :meth:`exchange` reduces (inexact leaves
        only) at the strategy's wire dtype, times the ring traffic factor
        ``2*(n-1)/n`` applied once to the total element count per dtype.
        ``zero1`` moves the same total: ``(n-1)/n`` of the grad buckets out
        (reduce-scatter) plus ``(n-1)/n`` of the param buckets back
        (all-gather), both at the leaf dtype.  Bucket padding and
        ``ring_int8``'s per-chunk fp32 scales are excluded (<0.1% at 4 MiB
        buckets) so the compression invariants stay EXACT: ``psum_bf16*``
        reports exactly ½ and ``ring_int8`` exactly ¼ of ``psum`` for the
        same tree.  ``tree`` may hold arrays or ``ShapeDtypeStruct``s.
        """
        if axis_size <= 1 or self.strategy == "none":
            return 0
        per_dtype: dict = {}
        for leaf in jax.tree.leaves(tree):
            shape, dtype = _leaf_meta(leaf)
            if not jnp.issubdtype(dtype, jnp.inexact):
                continue
            per_dtype[dtype] = per_dtype.get(dtype, 0) + math.prod(shape)
        total = 0
        for dtype, elems in per_dtype.items():
            wire_elems = 2 * (axis_size - 1) * elems // axis_size
            total += wire_elems * wire_itemsize(self.strategy, dtype)
        return total

    def bucket_summary(self, tree, axis_size: int) -> dict | None:
        """Bucket-count/byte summary for telemetry's one-time accounting
        event; None for leaf-wise strategies."""
        if not self.bucketed:
            return None
        buckets = self.zero1_layout(tree, axis_size)
        return {
            "n_buckets": len(buckets),
            "bucket_bytes": self.bucket_bytes,
            "padded_bytes": sum(
                b.padded * jnp.dtype(b.dtype).itemsize for b in buckets),
        }

    def __repr__(self):
        extra = ", overlap=True" if self.overlap else ""
        return (f"Exchanger(strategy={self.strategy!r}, "
                f"axis={self.axis_name!r}{extra})")
