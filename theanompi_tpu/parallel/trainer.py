"""Shared trainer/rule scaffolding for the three parallel rules.

Reference (unverified — SURVEY.md §2.1): the per-rule worker scripts
(``bsp_worker.py``, ``easgd_worker.py``/``easgd_server.py``,
``gosgd_worker.py``) share their epoch/validation/recording skeleton and
differ in how parameters are exchanged.  Here the skeleton is
:class:`BaseTrainer` (compile → iterate → validate → record) and each rule
supplies the compiled step + parameter layout:

- BSP: one replicated parameter set, exchange fused into the step;
- EASGD/GOSGD: *per-worker divergent* parameter sets, stored stacked along a
  leading axis sharded over the ``data`` mesh axis, with periodic host-driven
  exchange steps (the SPMD reformulation of the reference's async MPI
  messages — see each module's docstring).
"""

from __future__ import annotations

import os
import sys
import time
from contextlib import nullcontext
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from theanompi_tpu.resilience import (
    NonFiniteLossError,
    PreemptGuard,
    PreemptionExit,
    PreemptionRequested,
    ResilienceConfig,
    SentinelRollback,
)

from theanompi_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    make_mesh,
    replica_rng,
)
from theanompi_tpu.utils.helper_funcs import import_model, shard_batch
from theanompi_tpu.utils.recorder import Recorder


from theanompi_tpu.parallel.exchanger import (  # noqa: E402
    EXCHANGE_RNG_TAG as _EXCH_RNG_TAG,
    fused_pmean,
)


def pmean_floats(tree, axis_name):
    """pmean every inexact leaf; pass ints (counters etc.) through.

    Fused: one collective per dtype instead of one per leaf (a BN-state
    tree of 16 running-stat buffers costs ONE all-reduce) — part of the
    bucketed-exchange HLO budget ``tests/test_lint_collectives.py`` locks.
    """
    return fused_pmean(tree, axis_name)


def unstack(tree):
    """Drop the per-shard leading worker axis of size 1 (inside shard_map)."""
    return jax.tree.map(lambda x: x[0], tree)


def restack(tree):
    return jax.tree.map(lambda x: x[None], tree)


def make_local_step(model, opt, base_key, exchanger=None, stacked=False,
                    param_specs=None, sentinel_skip=False):
    """The per-worker train step shared by every rule.

    ``exchanger`` set (BSP): gradients are mean-reduced across the data axis
    before the update, and metrics/state are pmean'd so the outputs are
    replicated.  ``stacked`` (EASGD/GOSGD): parameter trees carry a leading
    worker axis of size 1 per shard, the step is collective-free, and metrics
    come back per-worker (stacked) — averaging happens on host at print time.
    ``param_specs`` (tensor parallelism) makes gradient clipping's global
    norm exact across model shards (see :func:`ops.opt.global_sq_norm`).

    ``n_subb`` in the model config (reference contract: the file-batch was
    trained in ``n_subb`` sub-batches with cumulative gradients —
    SURVEY.md §2.3/§2.4.1) enables gradient accumulation: the per-worker
    batch is split into ``n_subb`` micro-batches and a ``lax.scan`` runs
    forward+backward per micro-batch, summing gradients and threading
    model state sequentially, with ONE exchange and ONE optimizer update
    per step.  Activation memory is per-micro-batch — on TPU this is the
    lever for large effective batches at fixed HBM.  Numerics: with
    per-example normalization (LN) the accumulated mean gradient equals
    the full-batch gradient exactly; with batch-statistic layers (BN)
    statistics are per-micro-batch, the same semantics the reference's
    sub-batched training had.
    """
    n_subb = int(model.config.get("n_subb", 1) or 1)

    # models with a non-standard update (e.g. the GAN two-optimizer step)
    # supply the whole inner step; the rule still owns layout and reduction
    custom = getattr(model, "make_custom_step", None)
    inner = custom(opt, base_key, exchanger) if custom is not None else None
    if inner is not None and n_subb > 1:
        raise ValueError(
            f"n_subb={n_subb} requires the standard grad step; "
            f"{type(model).__name__} supplies make_custom_step"
        )
    if inner is not None and exchanger is not None and exchanger.fuses_update:
        raise ValueError(
            f"exch_strategy 'zero1' requires the standard grad step; "
            f"{type(model).__name__} supplies make_custom_step"
        )
    if inner is not None and sentinel_skip:
        raise ValueError(
            f"sentinel policy 'skip_batch' requires the standard grad step; "
            f"{type(model).__name__} supplies make_custom_step "
            f"(use sentinel_policy='abort' or 'rollback')"
        )

    def local_step(params, state, opt_state, batch, lr, step):
        if stacked:
            params, state, opt_state = (
                unstack(params), unstack(state), unstack(opt_state)
            )
        if inner is not None:
            new_params, new_state, new_opt_state, metrics = inner(
                params, state, opt_state, batch, lr, step
            )
        else:
            # fold every batch-sharding axis (dropout must differ per data
            # AND seq shard; it must NOT differ across model shards, whose
            # activations are jointly one logical tensor)
            axes = exchanger.axis_name if exchanger is not None else DATA_AXIS
            rng = replica_rng(jax.random.fold_in(base_key, step), axes)

            if n_subb == 1:
                def lossw(p):
                    return model.loss_fn(p, state, batch, rng, train=True)

                (_, (new_state, metrics)), grads = jax.value_and_grad(
                    lossw, has_aux=True
                )(params)
            else:
                new_state, metrics, grads = _accumulated_grads(
                    model, params, state, batch, rng, n_subb
                )
            ok = None
            if sentinel_skip:
                # the non-finite guard (ISSUE 4): ok iff loss AND the local
                # grad-norm² are finite on EVERY worker — the indicator is
                # psum'd across the exchange axes so replicas select the
                # same branch (critical for zero1, whose local grads may be
                # non-finite on only one shard)
                gsq = jnp.float32(0)
                for g in jax.tree.leaves(grads):
                    if jnp.issubdtype(g.dtype, jnp.inexact):
                        gsq = gsq + jnp.sum(jnp.square(g.astype(jnp.float32)))
                bad = jnp.logical_not(jnp.isfinite(gsq)).astype(jnp.float32)
                c = metrics.get("cost") if isinstance(metrics, dict) else None
                if c is not None:
                    bad = jnp.maximum(bad, jnp.logical_not(
                        jnp.all(jnp.isfinite(c))).astype(jnp.float32))
                if exchanger is not None:
                    bad = jax.lax.psum(bad, exchanger.axis_name)
                ok = bad == 0
            if exchanger is not None and exchanger.fuses_update:
                # zero1: the exchange IS the update — reduce-scatter grad
                # buckets, shard-local optimizer step, all-gather params
                # (opt_state lives in the exchanger's sharded bucket layout)
                new_params, new_opt_state = exchanger.exchange_and_update(
                    grads, opt_state, params, lr, opt,
                    rng=jax.random.fold_in(rng, _EXCH_RNG_TAG),
                    step=step,
                )
            else:
                if exchanger is not None:
                    # a distinct stream from dropout's: ring_int8 seeds its
                    # stochastic rounding from this key.  step anchors the
                    # overlap fence chain (exch_overlap; unused otherwise)
                    grads = exchanger.exchange(
                        grads, rng=jax.random.fold_in(rng, _EXCH_RNG_TAG),
                        step=step)
                new_params, new_opt_state = opt.update(
                    grads, opt_state, params, lr, param_specs=param_specs
                )
            if ok is not None:
                # skip_batch: a poisoned step costs one skipped update —
                # keep the old params/state/opt state wholesale
                def keep(new, old):
                    return jax.tree.map(lambda a, b: jnp.where(ok, a, b),
                                        new, old)

                new_params = keep(new_params, params)
                new_opt_state = keep(new_opt_state, opt_state)
                new_state = keep(new_state, state)
                if isinstance(metrics, dict):
                    # the host-side Sentinel pops this flag and enforces
                    # the bounded skip budget at fenced boundaries
                    metrics = dict(metrics)
                    metrics["_sentinel_skip"] = 1.0 - ok.astype(jnp.float32)
        if stacked:
            return (
                restack(new_params),
                restack(new_state),
                restack(new_opt_state),
                jax.tree.map(lambda m: m[None], metrics),
            )
        axes = exchanger.axis_name if exchanger is not None else DATA_AXIS
        metrics = pmean_floats(metrics, axes)
        # keep non-learned state consistent across replicas (already
        # identical under sync-BN; pmean repairs drift otherwise)
        new_state = pmean_floats(new_state, axes)
        if isinstance(metrics, dict):
            # the donated-device-step contract: train_iter pops this and
            # feeds it back as the next step argument, so the counter never
            # re-crosses the host boundary (one H2D transfer per run, not
            # per step)
            metrics = dict(metrics)
            metrics["_next_step"] = step + jnp.int32(1)
        return new_params, new_state, new_opt_state, metrics

    return local_step


def _accumulated_grads(model, params, state, batch, rng, n_subb):
    """Micro-batched forward+backward: -> (new_state, metrics, mean grads).

    One compiled ``lax.scan`` over ``n_subb`` micro-batches — activations
    live only for the current micro-batch; the gradient accumulator is one
    params-sized tree.  State (BN running stats) threads sequentially
    through the scan; float metrics come back micro-batch-averaged.
    """
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(batch)}
    if any(b % n_subb for b in leading):
        raise ValueError(
            f"n_subb={n_subb} must divide the per-worker batch "
            f"(got leading dims {sorted(leading)})"
        )

    def split(x):
        return x.reshape(n_subb, x.shape[0] // n_subb, *x.shape[1:])

    micro = jax.tree.map(split, batch)

    def one(carry, xs):
        st, gsum = carry
        mb, i = xs

        def lossw(p):
            # a fresh fold per micro-batch: dropout masks must differ
            # across micro-batches like they do across examples
            return model.loss_fn(p, st, mb, jax.random.fold_in(rng, i),
                                 train=True)

        (_, (new_st, m)), g = jax.value_and_grad(lossw, has_aux=True)(params)
        return (new_st, jax.tree.map(jnp.add, gsum, g)), m

    gsum0 = jax.tree.map(jnp.zeros_like, params)
    (new_state, gsum), mstk = jax.lax.scan(
        one, (state, gsum0), (micro, jnp.arange(n_subb))
    )
    grads = jax.tree.map(lambda g: g / n_subb, gsum)
    metrics = jax.tree.map(
        lambda m: (jnp.mean(m, axis=0)
                   if jnp.issubdtype(m.dtype, jnp.inexact) else m[-1]),
        mstk,
    )
    # perplexity is exp(loss): mean-of-exp over micro-batches would bias it
    # high vs an n_subb=1 run (Jensen) — re-derive from the averaged cost,
    # which is exactly what the unaccumulated path reports
    if isinstance(metrics, dict) and {"perplexity", "cost"} <= metrics.keys():
        metrics["perplexity"] = jnp.exp(metrics["cost"])
    return new_state, metrics, grads


def make_local_eval(model, axes=DATA_AXIS):
    """Shared eval step: params per their specs, batch per its partition."""

    def local_eval(params, state, batch):
        _, (_, metrics) = model.loss_fn(params, state, batch, None, train=False)
        return pmean_floats(metrics, axes)

    return local_eval


def require_data_parallel_mesh(mesh, rule_name: str) -> None:
    """Refuse tp/sp/pp meshes for the async rules (data-parallel only).

    EASGD/GOSGD stack per-worker params over ``data`` and ignore the
    model's ``param_specs`` — on a mesh with a sharded ``model``/``seq``/
    ``pipe`` axis, a tensor-parallel layer's collectives would run against
    replicated full weights and silently double-count (the same bug class
    the pipeline model guards against).  The reference's async rules were
    data-parallel only too (SURVEY.md §2.1).
    """
    for axis in (MODEL_AXIS, SEQ_AXIS, PIPE_AXIS):
        if mesh.shape.get(axis, 1) > 1:
            raise ValueError(
                f"{rule_name} is data-parallel only: mesh axis {axis!r} has "
                f"size {mesh.shape[axis]} (use BSP for tp/sp/pp shardings)"
            )


def _parse_profile_window(value) -> tuple:
    """ISSUE 16: normalize the ``profile_window`` rule key (tuple or the
    launcher's ``START:STOP`` string) — lazy import keeps the telemetry
    layer off the import path of telemetry-less runs."""
    from theanompi_tpu.telemetry.profile import parse_profile_window

    return parse_profile_window(value)


def stack_for_workers(mesh, tree, n: int):
    """Tile a pytree with a leading worker axis sharded over ``data``.

    The device layout of "every worker has its own copy" — each leaf becomes
    ``(n, *shape)`` with shard ``i`` resident on worker ``i``'s devices.
    """
    from theanompi_tpu.utils.helper_funcs import put_global

    sharding = NamedSharding(mesh, P(DATA_AXIS))

    def tile(x):
        x = np.asarray(x)
        return put_global(np.broadcast_to(x, (n, *x.shape)).copy(), sharding)

    return jax.tree.map(tile, tree)


class BaseTrainer:
    """Compile-and-iterate skeleton; subclasses define the step + layout.

    Subclass obligations: ``compile_iter_fns`` (set ``_step_fn``/``_eval_fn``),
    ``init_state``, ``eval_args()`` -> (params, state) for validation, and
    optionally ``post_step()`` (periodic exchange hook, called after every
    train iteration with ``self.iteration`` already advanced).
    """

    def __init__(self, model, mesh=None, recorder: Recorder | None = None,
                 seed: int = 0, prefetch_depth: int = 2,
                 checkpoint_dir: str | None = None, checkpoint_keep: int = 3,
                 checkpoint_async: bool = True,
                 checkpoint_verify: str = "auto",
                 checkpoint_every_n_iters: int = 0,
                 resume_force: bool = False,
                 resume_reshard: bool = False,
                 profile_dir: str | None = None,
                 profile_window: tuple[int, int] = (10, 20),
                 telemetry=None,
                 resilience: ResilienceConfig | None = None):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh(n_data=1)
        self.n_workers = self.mesh.shape[DATA_AXIS]
        self.recorder = recorder or Recorder()
        self.seed = seed
        self.prefetch_depth = prefetch_depth
        self.batch_spec = model.batch_partition()
        # ISSUE 4 resilience: a default config is all-off (env-gated by the
        # supervisor), so a bare trainer behaves exactly as before — every
        # hot-path hook below guards on `is None`
        self.resilience = (resilience if resilience is not None
                           else ResilienceConfig())
        self.fault_plan = self.resilience.build_fault_plan()
        self.sentinel = self.resilience.build_sentinel(telemetry)
        self._watchdog = None
        self._heartbeat = None  # liveness-only writer when detector is off
        self._preempt_guard = None
        self._epoch_start_iter = 0
        self.checkpointer = None
        if checkpoint_verify not in ("auto", "fast", "full", "none"):
            raise ValueError(
                f"checkpoint_verify must be auto/fast/full/none, "
                f"got {checkpoint_verify!r}")
        self.checkpoint_verify = checkpoint_verify
        # ISSUE 10: mid-epoch save cadence in iterations (0 = boundary-only,
        # the old behavior).  Cadence saves stamp the data-plane cursor into
        # the manifest, so a SIGKILL between boundaries resumes at the
        # newest iteration — replaying no batch and skipping none
        self.checkpoint_every_n_iters = int(checkpoint_every_n_iters or 0)
        if self.checkpoint_every_n_iters < 0:
            raise ValueError(
                f"checkpoint_every_n_iters must be >= 0, "
                f"got {checkpoint_every_n_iters}")
        self._resume_data_state: dict | None = None
        # batch-trace witness (ISSUE 10 tests/debug): when set, one line
        # per consumed global batch — "epoch batch_index" — is appended,
        # so no-replay/no-skip across a crash is a file diff
        self._data_trace_path = os.environ.get("THEANOMPI_DATA_TRACE")
        if checkpoint_dir:
            from theanompi_tpu.utils.checkpoint import Checkpointer

            # async by default (ISSUE 3): the boundary only pays the
            # snapshot; serialization/publish/prune run on the writer.
            # The fingerprint is the bound method, resolved lazily —
            # subclasses set self.exchanger after this constructor runs
            # (rules with a bucketed exchanger also backfill bucket_bytes
            # so the ISSUE 8 reshard planner recomputes the same layout)
            self.checkpointer = Checkpointer(
                checkpoint_dir, keep=checkpoint_keep,
                async_save=checkpoint_async, telemetry=telemetry,
                fault_plan=self.fault_plan,
                fingerprint=self._run_fingerprint,
                resume_force=resume_force,
                reshard=resume_reshard)
        self.optimizer = model.build_optimizer()
        self.global_batch = model.batch_size * self.n_workers
        # ISSUE 8: an elastic resume onto a different device count scales
        # the LR by new_n/old_n (linear-scaling rule — LR tracks the
        # global batch at fixed per-worker batch); 1.0 = no reshard
        self.lr_scale = 1.0
        self._step_fn = None
        self._eval_fn = None
        self.params = None
        self.state = None
        self.opt_state = None
        self.epoch = 0
        self.iteration = 0
        # SURVEY.md §5 tracing row: a bounded jax.profiler window
        # (TensorBoard-viewable device trace), off unless profile_dir is set
        self.profile_dir = profile_dir
        self.profile_window = profile_window
        self._profiling = False
        # ISSUE 1 telemetry: None means OFF — every hot-path integration
        # below guards on it, so a disabled run makes zero telemetry calls
        self.telemetry = telemetry
        self.recorder.telemetry = telemetry
        # ISSUE 10: the data layer's read-retry telemetry and fault hooks
        # are module-level (datasets outlive trainers and run on loader
        # threads/processes); wire only when there is something to wire,
        # so a bare trainer never clobbers hooks a test installed
        if telemetry is not None or self.fault_plan is not None:
            from theanompi_tpu.models.data.base import set_data_hooks

            set_data_hooks(telemetry=telemetry, fault_plan=self.fault_plan)
        self._compiled_step_cache: tuple | None = None  # (shape key, exe)
        self._exchange_wire_bytes_cached: int | None = None
        # per-step host->device scalar hoisting (ISSUE 2 satellite): the
        # placed lr is cached until the schedule changes it, and the step
        # counter round-trips as a device scalar (the step returns
        # `_next_step`, fed back as the next call's argument)
        self._lr_dev = None
        self._lr_host: float | None = None
        self._step_dev = None
        self._step_dev_iter: int = -1
        self._flops_per_step: float | None = None  # None = not yet probed
        self._peak_flops: float | None = None
        self._last_metrics_flush: float | None = None
        self._first_step_emitted = False  # compile.first_step_s gauge latch

    # -- subclass surface ----------------------------------------------------
    def compile_iter_fns(self) -> None:
        raise NotImplementedError

    def init_state(self) -> None:
        raise NotImplementedError

    def eval_args(self):
        """-> (params, state) to evaluate with (replicated)."""
        return self.params, self.state

    def compiled_step(self, batch):
        """The compiled train-step executable (serves ``.cost_analysis()``
        and ``.as_text()`` for bench/roofline tooling without each caller
        re-deriving the argument tuple).

        Memoized on the batch's shapes/dtypes (lowering is shape-based):
        ``lower().compile()`` is a full second XLA compile, which the
        telemetry MFU probe must not pay inside the train loop — and
        roofline's compiled_step + compiled_step_text pair now compiles
        once instead of twice."""
        import jax.numpy as jnp

        key = jax.tree.map(lambda x: (tuple(x.shape), str(x.dtype)), batch)
        if self._compiled_step_cache is not None \
                and self._compiled_step_cache[0] == key:
            return self._compiled_step_cache[1]
        args = (self.params, self.state, self.opt_state, batch,
                jnp.float32(0.01), jnp.int32(0))
        exe = self._step_fn.lower(*args).compile()
        self._compiled_step_cache = (key, exe)
        return exe

    def compiled_step_text(self, batch) -> str:
        """HLO text of the compiled train step (roofline/bench tooling)."""
        return self.compiled_step(batch).as_text()

    def post_step(self) -> None:
        """Periodic host-driven exchange hook (EASGD/GOSGD)."""

    def warmup_exchange(self) -> None:
        """Execute the rule's periodic-exchange compiled path once (jit is
        lazy; ``post_step`` may not fire it on the first iterations)."""

    def warmup(self) -> None:
        """Run every compiled path once, then reset to a fresh init.

        Timing harnesses (bench, rulecomp) call this so their measured
        window excludes XLA compilation: jit compiles at first call, not at
        ``compile_iter_fns`` (which only builds the jit wrappers).
        """
        gen = self.model.data.train_batches(self.global_batch, 0,
                                            seed=self.seed)
        try:
            batch = next(iter(gen))
        finally:
            # run()-loop parity: a prefetch-backed generator left unclosed
            # here would keep its worker thread/queue alive
            close = getattr(gen, "close", None)
            if close:
                close()
        self.train_iter(batch, lr=self.model.adjust_hyperp(0))
        self.warmup_exchange()
        # one val batch compiles the eval + consensus paths; a full
        # validate() would walk the whole val set untimed but for real
        vb = min(self.global_batch, self.model.data.n_val)
        vb -= vb % self.n_workers  # same divisibility rule as validate()
        if vb:
            vgen = self.model.data.val_batches(vb)
            try:
                vbatch = next(iter(vgen), None)
            finally:
                vclose = getattr(vgen, "close", None)
                if vclose:
                    vclose()
            if vbatch is not None:
                self.val_iter(vbatch)
        self.init_state()
        self.reset_iter()

    def reset_iter(self) -> None:
        """Zero the iteration/epoch counters and start a fresh recorder
        (reference contract name — its ``reset_iter(mode)`` re-armed the
        per-mode iteration state between phases; here counters live on the
        trainer and the compiled fns are mode-less pure functions, so a
        reset is just counters + recorder)."""
        self.iteration = 0
        self.epoch = 0
        self._resume_data_state = None
        self.recorder = Recorder(
            print_freq=self.recorder.print_freq,
            save_dir=self.recorder.save_dir,
            verbose=self.recorder.verbose,
            telemetry=self.telemetry,
        )

    def check_divergence(self, atol: float = 0.0) -> float:
        """Assert replicated param/state copies are in sync across devices.

        Debug hook (SURVEY.md §5 race-detection row): call at epoch
        boundaries when chasing non-determinism or exchange bugs; costs a
        device→host pull of the trees.
        """
        from theanompi_tpu.utils.divergence import assert_replicas_in_sync

        d1 = assert_replicas_in_sync(self.params, atol=atol, what="params")
        d2 = assert_replicas_in_sync(self.state, atol=atol, what="state")
        return max(d1, d2)

    def checkpoint_trees(self) -> dict:
        """Named pytrees a checkpoint must capture (rules add extras)."""
        return {
            "params": self.params,
            "state": self.state,
            "opt_state": self.opt_state,
        }

    def _run_fingerprint(self) -> dict:
        """The run-topology fingerprint stamped into checkpoint manifests
        (ISSUE 5): resuming under a different mesh, exchange strategy,
        accumulation depth, or model config is a hard refusal unless
        ``resume_force`` — a silent topology change corrupts the lineage
        (zero1 opt-state shards, stacked EASGD/GOSGD worker axes, and RNG
        streams all depend on it).  The model-identity half is
        :func:`~theanompi_tpu.utils.checkpoint.model_fingerprint` — ONE
        sha definition shared with the serving consumer, so a ``tmserve``
        process built from the same ``--set`` flags matches a training
        manifest (see ``MODEL_FP_EXCLUDED`` there for why
        ``n_epochs``/``verbose``/``bn_axis`` don't hash).
        """
        from theanompi_tpu.utils.checkpoint import model_fingerprint

        exch = getattr(self, "exchanger", None)
        return {
            "mesh": {str(a): int(s) for a, s in self.mesh.shape.items()},
            "exchange": getattr(exch, "strategy", type(self).__name__),
            "n_subb": int(self.model.config.get("n_subb", 1) or 1),
            **model_fingerprint(self.model),
            **self._fingerprint_extra(),
        }

    def _fingerprint_extra(self) -> dict:
        """Subclass hook for extra (or overriding) fingerprint entries —
        BSP uses it to stamp the ramp-invariant base exchange strategy
        plus the ``exch_ramp``/``exch_overlap`` knobs, so a checkpoint
        written mid-ramp still matches a resume that starts at the base."""
        return {}

    def _maybe_ramp(self, epoch: int) -> None:
        """Subclass hook, called at the top of every epoch: activate the
        ``exch_ramp`` phase ``epoch`` dictates (no-op without a ramp).
        See :class:`theanompi_tpu.parallel.overlap.RampSchedule`."""

    def _data_state(self, epoch: int, completed: bool) -> dict:
        """The data-plane position a checkpoint captures (ISSUE 10).

        The cursor is stored in SAMPLES, not this run's batches: an
        elastic resume divides by ITS OWN global batch, so a mesh8->4
        restart consumes the exact same global sample sequence the mesh8
        run would have.  ``dataset`` is :meth:`Dataset.state` — cursors
        that persist ACROSS epochs (stream mixture cursors), restored on
        boundary resumes too, not just mid-epoch ones.
        """
        cursor = max(0, self.iteration - self._epoch_start_iter)
        return {
            "version": 1,
            "epoch": int(epoch),
            "completed": bool(completed),
            "batch_cursor": int(cursor),
            "sample_cursor": int(cursor) * int(self.global_batch),
            "global_batch": int(self.global_batch),
            "seed": int(self.seed),
            "dataset": self.model.data.state(),
        }

    def save_checkpoint(self, epoch: int, completed: bool = True):
        """Kick off a checkpoint save; -> SaveHandle (or None, no dir).

        ``completed=False`` (ISSUE 10) marks a MID-epoch save (iteration
        cadence, preemption): the manifest's ``data_state`` carries the
        consumed-batch cursor and ``try_resume`` re-enters the epoch there
        instead of treating it as finished.

        The training thread pays only the blocking snapshot (multi-host
        gathers + overlapped device→host copies + a cheap recorder-history
        list copy), emitted as the ``checkpoint.snapshot`` span inside the
        checkpointer; serialization, atomic publish, the recorder-history
        write and pruning run on the background writer (``checkpoint.write``
        span) unless ``checkpoint_async=False``.
        """
        if self.checkpointer is None:
            return None
        return self.checkpointer.save(
            epoch, self.iteration, self.checkpoint_trees(),
            recorder_snapshot=self.recorder.history_snapshot(),
            lr_scale=self.lr_scale,
            data_state=self._data_state(epoch, completed))

    def _resume_verify_level(self) -> str:
        """ISSUE 5 verify policy: the cheap structural check always; the
        full per-leaf hash read exactly when it pays — the first resume
        after a non-clean exit (the previous writer never reached its
        clean-shutdown handshake, or this is a supervised restart), which
        is when torn writes and half-copied files actually appear."""
        if self.checkpoint_verify != "auto":
            return self.checkpoint_verify
        from theanompi_tpu.resilience.faults import current_attempt

        if self.checkpointer.was_unclean() or current_attempt() > 1:
            return "full"
        return "fast"

    def try_resume(self) -> bool:
        """Restore the newest *verifiable* checkpoint; -> resumed or not.

        Call after ``init_state`` (the fresh state is the restore template,
        carrying pytree structure and shardings).  Goes through the
        checkpoint recovery chain (ISSUE 5): corrupt checkpoints are
        quarantined and stepped over; an exhausted chain raises
        :class:`~theanompi_tpu.utils.checkpoint.CheckpointChainExhausted`
        (tmlauncher exit 77) and a run-topology mismatch raises
        :class:`~theanompi_tpu.utils.checkpoint.CheckpointFingerprintError`
        unless ``resume_force`` is set."""
        if self.checkpointer is None:
            return False
        res = self.checkpointer.load_latest_verified(
            self.checkpoint_trees(), verify=self._resume_verify_level())
        if res is None:
            return False
        epoch, iteration, restored = res
        for name, tree in restored.items():
            setattr(self, name, tree)  # params/state/opt_state + rule extras
        ds = (self.checkpointer.last_loaded_manifest or {}).get("data_state")
        if ds and not ds.get("completed", True):
            # mid-epoch checkpoint (ISSUE 10): re-enter the saved epoch at
            # the saved cursor — _run_epochs fast-forwards the data plane
            # by cursor arithmetic, replaying nothing and skipping nothing
            self.epoch = int(ds.get("epoch", epoch))
            self._resume_data_state = dict(ds)
        else:
            self.epoch = epoch + 1  # that epoch completed
        self.iteration = iteration
        if ds and isinstance(ds.get("dataset"), dict) and ds["dataset"]:
            # dataset-internal cursors (stream mixture positions) persist
            # ACROSS epochs: restore them on boundary resumes too
            self.model.data.set_state(ds["dataset"])
        plan = self.checkpointer.last_reshard_plan
        if plan is not None:
            # ISSUE 8: the load replanned a topology change — apply the
            # (cumulative) linear-scaling LR factor for the rest of the
            # run and say so loudly (a silently rescaled LR would read as
            # a lineage bug)
            self.lr_scale = plan.lr_scale
            lr_note = (
                "LR carried unrescaled (async rule: per-worker batch and "
                "update are n-independent)"
                if getattr(plan, "stacked", None) is not None
                else f"LR scaled x{plan.lr_scale:g} (linear-scaling rule)")
            print(f"trainer: RESHARD resumed a {plan.old_n}-worker "
                  f"checkpoint onto {self.n_workers} workers: global batch "
                  f"{self.model.batch_size * plan.old_n} -> "
                  f"{self.global_batch} (per-worker batch fixed), "
                  f"{lr_note}", file=sys.stderr, flush=True)
        else:
            # a plain resume of a previously-resharded lineage keeps its
            # cumulative LR factor (stamped in the manifest)
            man = self.checkpointer.last_loaded_manifest
            if man is not None:
                self.lr_scale = float(man.get("lr_scale", 1.0) or 1.0)
        self.recorder.load(self.checkpointer.directory)
        if self.recorder.verbose:
            where = (f"mid-epoch {self.epoch} at batch "
                     f"{self._resume_data_state.get('batch_cursor', 0)}"
                     if self._resume_data_state is not None
                     else f"epoch {epoch}")
            print(f"resumed from {where} "
                  f"(iteration {self.iteration})", flush=True)
        return True

    # -- profiling (SURVEY.md §5: jax.profiler traces) -----------------------
    def _profile_tick(self) -> None:
        """Start/stop the device trace at the configured iteration window.

        The window is [start, stop) in global iterations; steps inside it are
        captured to ``profile_dir`` (open with TensorBoard's profile plugin
        or Perfetto).  A bounded window, not whole-run tracing: traces are
        huge and perturb timing.  Stop fences on the params so the trace
        includes the full device execution of the last windowed step.
        """
        if self.profile_dir is None:
            return
        start, stop = self.profile_window
        # range membership, not equality: a resumed run (try_resume sets
        # iteration past `start`) must still trace if it's inside the window
        if not self._profiling and start <= self.iteration < stop:
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
            self._profile_mark("start")
        elif self._profiling and self.iteration >= stop:
            self._profile_stop()

    def _profile_stop(self) -> None:
        jax.block_until_ready(jax.tree.leaves(self.params))
        jax.profiler.stop_trace()
        self._profiling = False
        self._profile_mark("stop")

    def _profile_mark(self, phase: str) -> None:
        """ISSUE 16: stamp the trace window into the event stream so the
        device trace aligns with the host spans in one timeline."""
        if self.telemetry is None:
            return
        from theanompi_tpu.telemetry.metrics import PROF_INSTANTS

        self.telemetry.instant(PROF_INSTANTS[0], phase=phase,
                               iteration=self.iteration)

    # -- telemetry (ISSUE 1) -------------------------------------------------
    def exchange_wire_bytes(self) -> int | None:
        """Per-device ICI bytes for this rule's per-step exchange.

        Static accounting (the collective is fused into the XLA step, so
        nothing host-side can observe it): rules with a per-step exchanger
        (BSP) report ``Exchanger.wire_bytes`` of the gradient tree.  The
        per-device gradient buffer is the PARAM SHARD, not the global
        param (under tensor/sequence parallelism each device reduces only
        its slice), so leaves are sized via ``sharding.shard_shape`` —
        and the ring spans every exchange axis, so the traffic factor uses
        the product of the exchanger's axis sizes, not just ``data``.
        Rules without a per-step exchanger return None; their periodic
        exchanges account for themselves (see EASGD.post_step).
        """
        exch = getattr(self, "exchanger", None)
        if exch is None or self.params is None:
            return None
        return exch.wire_bytes(self._shard_param_structs(),
                               self._exchange_axis_size())

    def _exchange_axis_size(self) -> int:
        exch = getattr(self, "exchanger", None)
        if exch is None:
            return 1
        axes = (exch.axis_name if isinstance(exch.axis_name, tuple)
                else (exch.axis_name,))
        n = 1
        for a in axes:
            n *= self.mesh.shape.get(a, 1)
        return n

    def _shard_param_structs(self):
        """The per-device param-shard shapes the exchange actually moves."""

        def shard_struct(x):
            if isinstance(x, jax.Array) and x.sharding is not None:
                return jax.ShapeDtypeStruct(
                    x.sharding.shard_shape(x.shape), x.dtype)
            return x

        return jax.tree.map(shard_struct, self.params)

    def _exchange_accounting(self) -> int:
        """Cached per-step wire bytes; emits the one-time accounting event
        (strategy, bytes, worker count) the first time it resolves."""
        if self._exchange_wire_bytes_cached is None:
            wire = self.exchange_wire_bytes()
            self._exchange_wire_bytes_cached = 0 if wire is None else wire
            exch = getattr(self, "exchanger", None)
            if wire is not None and self.telemetry is not None:
                extra = exch.bucket_summary(
                    self._shard_param_structs(),
                    self._exchange_axis_size()) or {}
                self.telemetry.instant(
                    "exchange.accounting",
                    strategy=exch.strategy,
                    bytes_per_exchange=wire,
                    n_workers=self.n_workers,
                    **extra,
                )
        return self._exchange_wire_bytes_cached

    def _telemetry_flush(self, r: Recorder) -> None:
        """Publish live training metrics at the print boundary: rates,
        step-time percentiles, MFU, device memory high-water.

        The rate window is wall time since the previous flush; callers
        reset ``_last_metrics_flush`` to None across non-training work
        (validation, checkpointing — see run()) so a window never absorbs
        it and under-reports throughput.  A None window (first flush of a
        window) publishes no rate gauges rather than a wrong number.
        """
        from theanompi_tpu.telemetry import metrics as tmetrics

        tel = self.telemetry
        now = time.perf_counter()
        window_s = (now - self._last_metrics_flush
                    if self._last_metrics_flush is not None else None)
        self._last_metrics_flush = now
        if window_s:
            eps = r.print_freq * self.global_batch / window_s
            tel.gauge("train.examples_per_sec", eps)
            seq = self.model.config.get("seq_len")
            if seq:
                tel.gauge("train.tokens_per_sec", eps * seq)
        p50 = tel.metrics.percentiles("train.step_s", (50,)).get("p50")
        if self._flops_per_step and p50:
            m = tmetrics.mfu(self._flops_per_step, p50, self._peak_flops)
            if m is not None:
                tel.gauge("train.mfu", m)
        mem = tmetrics.device_memory_stats()
        if mem:
            for k, v in mem.items():
                tel.gauge(f"device.{k}", v)
        # ISSUE 16: attr.* segment gauges + per-device HBM watermarks +
        # ATTRIB.json refresh, all at this fenced boundary (no-op unless
        # the attributor was configured)
        tel.profile_flush(step=self.iteration)
        tel.flush_metrics(step=self.iteration, window_steps=r.print_freq)

    # -- iteration (reference train_iter/val_iter) ---------------------------
    def _apply_step_fault(self, batch):
        """Deterministic fault injection (ISSUE 4) — the `step` site."""
        from theanompi_tpu.resilience import faults

        action = self.fault_plan.fire("step", self.iteration)
        if action is None:
            return batch
        if action == "raise":
            raise faults.FaultInjected(
                f"injected failure at train step {self.iteration}")
        if action == "kill":
            faults.kill_self()
        # "nan": poison the batch's float leaves so the loss/grads become
        # genuinely non-finite — the sentinel sees the real article, not a
        # spoofed metric
        def poison(x):
            dt = getattr(x, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.inexact):
                return x * np.dtype(dt).type(float("nan"))
            return x

        return jax.tree.map(poison, batch)

    def train_iter(self, batch: dict, lr: float, recorder: Recorder | None = None):
        if self.fault_plan is not None:
            batch = self._apply_step_fault(batch)
        self._profile_tick()
        r = recorder or self.recorder
        tel = self.telemetry
        step_t0 = time.perf_counter() if tel is not None else 0.0
        step_idx, epoch_idx = self.iteration, self.epoch
        r.start("wait")
        # already-placed batches (prefetch path) pass through device_put free
        batch = shard_batch(self.mesh, batch, spec=self.batch_spec)
        r.end("wait")
        r.start("calc")
        # scalar-hoisting (ISSUE 2 satellite): jnp.float32(lr)/jnp.int32(i)
        # here were one host->device transfer EACH per step; the lr is
        # placed once per schedule change and the step counter is carried
        # as a device scalar threaded through the step's `_next_step`
        lr_f = float(lr)
        if self._lr_dev is None or self._lr_host != lr_f:
            self._lr_dev = jnp.float32(lr_f)
            self._lr_host = lr_f
        if self._step_dev is None or self._step_dev_iter != self.iteration:
            self._step_dev = jnp.int32(self.iteration)
        self.params, self.state, self.opt_state, metrics = self._step_fn(
            self.params,
            self.state,
            self.opt_state,
            batch,
            self._lr_dev,
            self._step_dev,
        )
        self.iteration += 1
        nxt = (metrics.pop("_next_step", None)
               if isinstance(metrics, dict) else None)
        if nxt is not None and getattr(nxt, "ndim", None) == 0:
            self._step_dev, self._step_dev_iter = nxt, self.iteration
        else:  # stacked/custom metrics carry no counter: re-place next call
            self._step_dev = None
        # the device guard's skip flag is sentinel bookkeeping, not a
        # training metric — pop it before the recorder sees the dict
        skipf = (metrics.pop("_sentinel_skip", None)
                 if isinstance(metrics, dict) else None)
        # fence only at print boundaries: per-iter blocking would serialize
        # the dispatch pipeline (SURVEY.md §7 hard part 5)
        fence = metrics["cost"] if self.iteration % r.print_freq == 0 else None
        r.end("calc", fence=fence)
        # no wrapping span here: the async rules' post_step brackets the
        # rounds that actually exchange with recorder 'comm' segments, which
        # the recorder already emits as spans — a per-step wrapper would
        # write a no-op span line on every non-exchange step (tau-1 of tau)
        self.post_step()
        r.end_iteration()
        r.train_metrics(**metrics)
        r.print_train_info(self.iteration)
        if tel is not None:
            # same async-dispatch honesty caveat as the calc split: between
            # print boundaries a span measures dispatch, and only the fenced
            # boundary step reflects full device time — percentile/rate
            # metrics below aggregate across a window, which is honest at
            # steady state because dispatched work must drain through the
            # donated-buffer chain
            dur = time.perf_counter() - step_t0
            # loss tag ONLY at fenced boundary steps (ISSUE 13): the cost
            # is already materialized by the calc fence above, so float()
            # is free here; tagging every step would add a per-step sync.
            # The health monitor's NaN/spike detector keys on this tag.
            if fence is not None:
                tel.emit_span("train.step", step_t0, dur, step=step_idx,
                              epoch=epoch_idx, loss=float(fence))
            else:
                tel.emit_span("train.step", step_t0, dur,
                              step=step_idx, epoch=epoch_idx)
            tel.observe("train.step_s", dur)
            if not self._first_step_emitted:
                # first-compile visibility (ISSUE 3): the first dispatch
                # pays tracing + XLA compile synchronously — or a
                # persistent-cache hit.  This gauge is the witness that
                # --compile-cache-dir works: a warm cache makes it drop.
                self._first_step_emitted = True
                tel.gauge("compile.first_step_s", dur, step=step_idx)
            wire = self._exchange_accounting()
            if wire:
                tel.count("exchange.wire_bytes", wire, emit=True,
                          step=step_idx)
            if self._flops_per_step is None:
                # MFU probe on the FIRST step, after its span closed: the
                # aot lower+compile lands next to the jit compile this
                # step already paid, instead of stalling the loop minutes
                # later at the first print boundary; its own span keeps
                # the cost visible rather than untracked
                from theanompi_tpu.telemetry import metrics as tmetrics

                with tel.span("telemetry.mfu_probe"):
                    self._flops_per_step = tmetrics.step_flops_estimate(
                        self, batch) or 0.0
                    self._peak_flops = tmetrics.peak_flops()
            if self.iteration % r.print_freq == 0:
                self._telemetry_flush(r)
        if self._watchdog is not None:
            self._watchdog.beat(self.iteration)
        elif self._heartbeat is not None:
            # detector disabled but a supervisor watches the file: keep
            # proving liveness or its --hang-timeout kills a healthy run
            self._heartbeat.beat(self.iteration)
        if self.sentinel is not None:
            # lazy refs now, materialization at the fenced print boundary:
            # the sentinel must not add a per-step device sync (same
            # discipline as the recorder's calc fence)
            self.sentinel.watch(
                step_idx,
                metrics.get("cost") if isinstance(metrics, dict) else None,
                skipf)
            if self.iteration % r.print_freq == 0:
                self.sentinel.check()
        return metrics

    def val_iter(self, batch: dict, recorder: Recorder | None = None,
                 eval_args=None):
        batch = shard_batch(self.mesh, batch, spec=self.batch_spec)
        # eval_args may be expensive (GOSGD consensus psums the whole param
        # tree) — validate() hoists it out of the per-batch loop
        params, state = eval_args if eval_args is not None else self.eval_args()
        return self._eval_fn(params, state, batch)

    def validate(self, epoch: int):
        # the val set may be smaller than the global batch; shrink to the
        # largest worker-divisible batch rather than silently skipping
        vb = min(self.global_batch, self.model.data.n_val)
        vb -= vb % self.n_workers
        if vb == 0:
            if self.recorder.verbose:
                print(
                    f"validate: n_val={self.model.data.n_val} < "
                    f"{self.n_workers} workers, skipping",
                    flush=True,
                )
            return {}
        accums: dict[str, list] = {}
        eval_args = self.eval_args()
        with (self.telemetry.span("validate", epoch=epoch)
              if self.telemetry is not None else nullcontext()):
            for batch in self.model.data.val_batches(vb):
                m = self.val_iter(batch, eval_args=eval_args)
                for k, v in m.items():
                    # device arrays accumulate WITHOUT float(): a per-batch
                    # float() forced a device sync per metric per batch,
                    # serializing the eval dispatch pipeline (ISSUE 2
                    # satellite) — the single pull happens after the loop
                    accums.setdefault(k, []).append(v)
        means = {
            k: float(np.asarray(jnp.stack(v)).mean(dtype=np.float64))
            for k, v in accums.items()
        }
        # perplexity is exp(loss): the arithmetic mean of per-batch
        # perplexities is Jensen-biased high — re-derive from the averaged
        # cost (same fix the micro-batch accumulation path applies)
        if {"perplexity", "cost"} <= means.keys():
            means["perplexity"] = float(np.exp(means["cost"]))
        self.recorder.val_metrics(epoch, **means)
        return means

    # -- full run (reference *_worker.run) -----------------------------------
    def _make_prefetcher(self, epoch: int, start_batch: int = 0):
        """The para_load equivalent: read/augment/transfer overlaps compute.

        ``start_batch`` (ISSUE 10): the resume cursor — the dataset
        fast-forwards to it by seed/cursor arithmetic (no batch is
        materialized to be thrown away) and the prefetcher's fault and
        consumption ordinals stay GLOBAL batch indices across the restart.
        """
        from theanompi_tpu.models.data.prefetch import prefetch

        return prefetch(
            self.model.data.train_batches(self.global_batch, epoch,
                                          seed=self.seed,
                                          start_batch=start_batch),
            mesh=self.mesh,
            depth=self.prefetch_depth,
            spec=self.batch_spec,
            telemetry=self.telemetry,
            # ISSUE 4: a hung source raises PrefetchStallError instead of
            # deadlocking the training thread forever (None keeps the old
            # block-forever behavior); the fault plan's `prefetch` site
            # lives inside the worker
            stall_timeout=self.resilience.prefetch_stall_timeout,
            fault_plan=self.fault_plan,
            start_batch=start_batch,
        )

    def _check_preempt(self) -> None:
        """Between-steps preemption poll (a host flag read, nothing more)."""
        if self._preempt_guard is not None and self._preempt_guard.triggered:
            raise PreemptionRequested()

    def _preemption_checkpoint(self) -> bool:
        """The final synchronous checkpoint of a preempted run.

        ISSUE 10: the state is labeled with the CURRENT epoch and carries
        the data-plane cursor (``completed=False``), so the resumed run
        re-enters the interrupted epoch at the first unconsumed batch —
        exactly-once data consumption, replacing the old at-least-once
        epoch replay (which re-trained every step since the boundary).
        When no step has run since the last boundary save there is
        nothing new to capture; the in-flight async writer (if any) is
        joined so the boundary checkpoint is durably published before
        exiting.
        """
        if self.checkpointer is None:
            return False
        if self.iteration <= self._epoch_start_iter:
            self.checkpointer.join_pending()
            return False
        handle = self.checkpointer.save(
            self.epoch, self.iteration, self.checkpoint_trees(),
            recorder_snapshot=self.recorder.history_snapshot(),
            lr_scale=self.lr_scale,
            data_state=self._data_state(self.epoch, completed=False))
        handle.join()  # synchronous: the process is about to exit
        self.checkpointer.join_pending()
        return True

    def _handle_rollback(self, e: SentinelRollback) -> None:
        """Reload the newest *verifiable* checkpoint in-process (sentinel
        'rollback').  Goes through the recovery chain (ISSUE 5): a
        NaN-triggered rollback whose latest checkpoint is corrupt
        quarantines it and lands on the verified ancestor instead of
        re-raising into a crash loop; an exhausted chain propagates as the
        typed checkpoint error (exit 77 under the launcher).  Still
        bounded by the existing ``sentinel_max_rollbacks`` budget."""
        self.sentinel.rollbacks += 1
        if (self.checkpointer is None
                or self.sentinel.rollbacks > self.sentinel.max_rollbacks):
            why = ("no checkpoint dir to roll back from"
                   if self.checkpointer is None else
                   f"rollback budget exhausted "
                   f"({self.sentinel.max_rollbacks})")
            raise NonFiniteLossError(
                f"non-finite loss at step {e.step}; {why}", step=e.step
            ) from e
        print(f"sentinel: non-finite loss at step {e.step}; rolling back "
              f"to the newest verifiable checkpoint "
              f"({self.sentinel.rollbacks}/{self.sentinel.max_rollbacks})",
              file=sys.stderr, flush=True)
        self.sentinel.reset_pending()  # pending losses describe a dead timeline
        if self._watchdog is not None:
            self._watchdog.pause()  # restore I/O + re-placement is beat-free
        try:
            resumed = self.try_resume()
        finally:
            if self._watchdog is not None:
                self._watchdog.resume()
        if not resumed:
            raise NonFiniteLossError(
                f"non-finite loss at step {e.step}; no checkpoint to roll "
                f"back to", step=e.step) from e
        if self.telemetry is not None:
            self.telemetry.instant("sentinel.rollback", step=e.step,
                                   restore_epoch=self.epoch - 1,
                                   rollback=self.sentinel.rollbacks)
        self._step_dev = None  # restored iteration needs a fresh device scalar

    def _run_epochs(self, stop=None) -> None:
        """The epoch loop proper (run() owns retry/teardown around it)."""
        model = self.model
        batches = None
        try:
            for epoch in range(self.epoch, model.n_epochs):
                self.epoch = epoch
                # quantization ramp (exch_ramp): the ONE place a phase can
                # switch — an epoch boundary, so at most one recompile per
                # phase and a resume lands in the phase its epoch dictates
                self._maybe_ramp(epoch)
                start_batch = 0
                rds, self._resume_data_state = self._resume_data_state, None
                if rds is not None and int(rds.get("epoch", -1)) == epoch:
                    # ISSUE 10: resume INSIDE this epoch.  The cursor is
                    # in samples (device-count-independent): an elastic
                    # resume divides by its OWN global batch, preserving
                    # the exact global sample order across a mesh change
                    sc = int(rds.get("sample_cursor", 0))
                    start_batch = sc // self.global_batch
                    if sc % self.global_batch:
                        print(f"trainer: resume sample cursor {sc} is not "
                              f"divisible by the global batch "
                              f"{self.global_batch}; flooring to batch "
                              f"{start_batch} (the partial batch replays)",
                              file=sys.stderr, flush=True)
                    self._epoch_start_iter = self.iteration - start_batch
                else:
                    self._epoch_start_iter = self.iteration
                self._check_preempt()
                self.recorder.start_epoch()
                # lr_scale is 1.0 except after an elastic reshard (x1.0 is
                # float-exact, so unresharded lineages are bit-unchanged)
                lr = model.adjust_hyperp(epoch) * self.lr_scale
                if batches is None:  # not pre-built at the last boundary
                    batches = self._make_prefetcher(epoch, start_batch)
                it = iter(batches)
                try:
                    while True:
                        # the dequeue is the real input stall (para_load's
                        # 'wait' — SURVEY.md §3.5); time it into the same
                        # per-iteration wait bucket train_iter's residual
                        # shard_batch adds to, so a starved pipeline reports
                        # wait > 0 instead of hiding the stall in untracked
                        # loop time
                        self.recorder.start("wait")
                        try:
                            batch = next(it)
                        except StopIteration:
                            self.recorder.cancel("wait")
                            break
                        self.recorder.end("wait")
                        self.train_iter(batch, lr)
                        if (self._data_trace_path
                                and jax.process_index() == 0):
                            # consumed-batch witness: (epoch, global batch
                            # index) of the step that just COMPLETED — a
                            # step killed inside train_iter leaves no line,
                            # so a resumed lineage's trace concatenates to
                            # exactly the uninterrupted sequence (the
                            # no-replay/no-skip assert in the e2e tests)
                            # lint: atomic-publish-ok — append-only
                            # witness lines; a torn tail IS the signal
                            # (the killed step leaves no complete line)
                            with open(self._data_trace_path, "a") as tf:
                                tf.write(
                                    f"{epoch} "
                                    f"{self.iteration - 1 - self._epoch_start_iter}"
                                    f"\n")
                        cad = self.checkpoint_every_n_iters
                        if (cad and self.checkpointer is not None
                                and (self.iteration
                                     - self._epoch_start_iter) % cad == 0):
                            # iteration-cadence mid-epoch save (ISSUE 10):
                            # stamps the data cursor; superseded by later
                            # cadence saves and the boundary save (same
                            # epoch label, atomic overwrite)
                            self.save_checkpoint(epoch, completed=False)
                        self._check_preempt()
                finally:
                    # a step failure must not leave the loader thread pinning
                    # device batches
                    close = getattr(batches, "close", None)
                    if close is not None:
                        close()
                    batches = None
                # boundary work is beat-free by nature (validation's first
                # eval compile, the val sweep, checkpoint joins): suspend
                # stall detection or a long boundary reads as a hang
                if self._watchdog is not None:
                    self._watchdog.pause()
                elif self._heartbeat is not None:
                    self._heartbeat.beat(self.iteration, force=True)
                if self.telemetry is not None:
                    # boundary bracket (ISSUE 13): the health monitor
                    # suspends hang detection between begin and end, for
                    # the same reason the watchdog pauses here
                    self.telemetry.instant("train.boundary", epoch=epoch,
                                           phase="begin")
                try:
                    if self.sentinel is not None:
                        # enforce pending observations BEFORE the boundary
                        # checkpoint: a state the policy rejects must never
                        # be the published resume point
                        self.sentinel.check()
                    # epoch-boundary overlap (ISSUE 3): build the NEXT
                    # epoch's prefetcher BEFORE validate + checkpoint, so
                    # its loader thread refills the input queue while the
                    # host validates and the checkpoint writer runs — the
                    # first post-boundary step no longer starts on a cold
                    # queue (its 'wait' segment is the witness)
                    if epoch + 1 < model.n_epochs:
                        batches = self._make_prefetcher(epoch + 1)
                    val = self.validate(epoch)
                    self.save_checkpoint(epoch)
                finally:
                    if self.telemetry is not None:
                        self.telemetry.instant("train.boundary",
                                               epoch=epoch, phase="end")
                    if self._watchdog is not None:
                        self._watchdog.resume()
                    elif self._heartbeat is not None:
                        self._heartbeat.beat(self.iteration, force=True)
                # progress up to here is durably labeled: a preemption
                # arriving before the next step must not re-save (and must
                # not regress the published iteration)
                self._epoch_start_iter = self.iteration
                if self.telemetry is not None:
                    # restart the rate window: validation + checkpoint time
                    # must not deflate the next examples/s gauge
                    self._last_metrics_flush = None
                self.epoch = epoch + 1  # resume point: next, not this one
                self._check_preempt()
                if stop is not None and stop(epoch, val):
                    break
        finally:
            # an early stop() or an exception leaves the pre-built next-epoch
            # prefetcher alive — close it so its thread stops pinning batches
            if batches is not None:
                close = getattr(batches, "close", None)
                if close is not None:
                    close()

    def run(self, stop=None):
        """Train to completion.

        ``stop``: optional ``(epoch, val_metrics) -> bool`` checked after each
        epoch's validation — a True ends training early (used by the
        rule-comparison harness for train-to-target runs).

        Resilience (ISSUE 4, all opt-in — see the resilience package):
        a sentinel 'rollback' reloads the latest checkpoint in-process and
        retries; a preemption signal lands as a final synchronous
        checkpoint plus a :class:`PreemptionExit` carrying the distinct
        resumable exit code; a watchdog thread (under supervision) turns a
        stalled loop into a restartable hang exit.
        """
        if self._step_fn is None:
            self.compile_iter_fns()
        if self.params is None:
            self.init_state()
        if (self.telemetry is not None
                and self.telemetry.flight is not None):
            # the blackbox dump of a crashed run carries the topology it
            # died under (mesh axes, exchange strategy, model identity)
            self.telemetry.flight.set_fingerprint(self._run_fingerprint())
        model = self.model
        guard = None
        if self.resilience.preemption_enabled():
            guard = PreemptGuard(telemetry=self.telemetry)
            if not guard.install():  # not the main thread: stay inactive
                guard = None
        self._preempt_guard = guard
        self._watchdog = self.resilience.build_watchdog(self.telemetry)
        if self._watchdog is not None:
            self._watchdog.start()
        else:
            self._heartbeat = self.resilience.build_heartbeat()
        try:
            while True:
                try:
                    self._run_epochs(stop)
                    break
                except SentinelRollback as e:
                    self._handle_rollback(e)  # may escalate NonFiniteLossError
        except PreemptionRequested:
            if self._watchdog is not None:
                # the final synchronous checkpoint is beat-free and must
                # not be killed as a hang (76 would burn restart budget;
                # this exit is the budget-free 75)
                self._watchdog.stop()
                self._watchdog = None
            saved = self._preemption_checkpoint()
            if self.checkpointer is not None:
                # the preemption checkpoint is synchronous and complete:
                # drop the dirty marker so the resumed attempt takes the
                # cheap fast verify, not the full hash read
                self.checkpointer.mark_clean()
            if self.telemetry is not None:
                self.telemetry.instant("preempt.exit", epoch=self.epoch,
                                       iteration=self.iteration,
                                       checkpointed=saved)
            self.recorder.save()
            model.cleanup()
            raise PreemptionExit(
                f"preempted at epoch {self.epoch}, iteration "
                f"{self.iteration}"
                + ("; resumable checkpoint saved" if saved else ""))
        finally:
            self._preempt_guard = None
            if guard is not None:
                guard.uninstall()
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None
            self._heartbeat = None
            # window ran past the end of training, OR an exception landed
            # inside it — either way the device trace must be stopped and
            # flushed, not leaked (the bounded-window contract)
            if self._profiling:
                self._profile_stop()
            # at most one in-flight checkpoint writer: exit joins it (like
            # the next save or a resume would), so a writer exception
            # surfaces here instead of dying with the daemon thread.  But
            # when a PRIMARY exception is already unwinding (often the same
            # root cause — full disk, dead mount), the writer's error must
            # not supersede it: report and let the original propagate (the
            # same correlated-failure discipline Rule.wait applies to
            # telemetry finalize)
            if self.checkpointer is not None:
                if sys.exc_info()[0] is None:
                    self.checkpointer.join_pending()
                else:
                    try:
                        self.checkpointer.join_pending()
                    except Exception as e:
                        print(f"checkpoint writer failed during teardown: "
                              f"{e}", file=sys.stderr)
        if self.checkpointer is not None:
            # clean-shutdown handshake (ISSUE 5): only a run that reaches
            # this line drops the dirty marker — the next resume of a
            # marker-holding directory pays the full-hash verify
            self.checkpointer.mark_clean()
        self.recorder.save()
        model.cleanup()
        return self.recorder


class Rule:
    """Reference-compatible rule facade shared by BSP/EASGD/GOSGD.

    Usage (mirrors the reference README pattern, SURVEY.md §3.1)::

        rule = BSP(config={"exch_strategy": "psum"})
        rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
                  modelclass="WideResNet")
        rule.wait()

    ``devices`` is a worker count, a list of jax devices, or None (all
    devices).  ``init`` builds the mesh and compiles; ``wait`` runs training
    to completion and returns the recorder (there is no process tree to join
    — the "cluster" is the mesh).
    """

    def __init__(self, config: dict[str, Any] | None = None):
        self.config = config or {}
        self.trainer: BaseTrainer | None = None

    def make_trainer(self, model, mesh, recorder) -> BaseTrainer:
        raise NotImplementedError

    def common_trainer_kwargs(self, recorder) -> dict:
        """Base-trainer kwargs every rule forwards."""
        return dict(
            recorder=recorder,
            seed=self.config.get("seed", 0),
            prefetch_depth=self.config.get("prefetch", 2),
            checkpoint_dir=self.config.get("checkpoint_dir"),
            checkpoint_keep=self.config.get("checkpoint_keep", 3),
            checkpoint_async=self.config.get("checkpoint_async", True),
            # ISSUE 5: verify mode (auto = fast, full after unclean exit)
            # and the fingerprint-mismatch override (--resume-force)
            checkpoint_verify=self.config.get("checkpoint_verify", "auto"),
            # ISSUE 10: mid-epoch save cadence in iterations (0 = off);
            # each cadence save stamps the data-plane cursor so a crash
            # resumes at the newest iteration, not the epoch start
            checkpoint_every_n_iters=int(
                self.config.get("checkpoint_every_n_iters", 0) or 0),
            resume_force=bool(self.config.get("resume_force", False)),
            # ISSUE 8: open the elastic reshard gate (--resume-reshard)
            resume_reshard=bool(self.config.get("resume_reshard", False)),
            profile_dir=self.config.get("profile_dir"),
            # ISSUE 16: parse, don't tuple() — a launcher-provided
            # ``--rule-set profile_window=10:20`` string would otherwise
            # silently become a 5-char tuple and never open the window
            profile_window=_parse_profile_window(
                self.config.get("profile_window", (10, 20))),
            telemetry=self.make_telemetry(),
            # ISSUE 4: fault_plan / sentinel_* / watchdog* / heartbeat_path /
            # handle_preemption / prefetch_stall_timeout rule keys (see
            # ResilienceConfig.KEYS); defaults are all-off
            resilience=ResilienceConfig.from_rule_config(self.config),
        )

    def make_telemetry(self):
        """Telemetry sink from config (``telemetry_dir`` et al.), or None.

        Per-rank sink files: each process of a multi-host pod writes its
        own ``events-rank*.jsonl`` under the same directory; rank 0
        aggregates whatever is visible at the end of :meth:`wait`.
        """
        directory = self.config.get("telemetry_dir")
        if not directory:
            return None
        from theanompi_tpu.telemetry import Telemetry

        return Telemetry(
            directory,
            max_bytes=self.config.get("telemetry_max_bytes", 32 * 2**20),
            keep=self.config.get("telemetry_keep", 3),
            # ISSUE 13: health detectors + crash flight recorder default ON
            # whenever telemetry itself is on.  ``telemetry_health`` takes
            # False, True, or a dict of HealthConfig overrides;
            # ``telemetry_blackbox`` is the event-ring capacity (0 = off)
            health=self.config.get("telemetry_health", True),
            flight_recorder=int(
                self.config.get("telemetry_blackbox", 256) or 0),
            # ISSUE 16: step-time attribution defaults ON with telemetry
            # (``telemetry_profile=False`` opts out); publishes ``attr.*``
            # gauges + ATTRIB.json from the existing event stream
            profile=self.config.get("telemetry_profile", True),
        )

    def adjust_model_config(self, model_config: dict, n_workers: int) -> None:
        """Rule-specific model-config defaults (e.g. sync-BN for BSP)."""

    def init(
        self,
        devices=None,
        modelfile: str = "theanompi_tpu.models.wide_resnet",
        modelclass: str = "WideResNet",
        model_config: dict | None = None,
    ):
        n_model = self.config.get("n_model", 1)
        n_seq = self.config.get("n_seq", 1)
        n_pipe = self.config.get("n_pipe", 1)
        if isinstance(devices, int):
            # `devices` is the WORKER (data-parallel) count, as in the
            # reference API; pipe/model/seq axes multiply the device need
            need = devices * n_model * n_seq * n_pipe
            mesh = make_mesh(n_data=devices, n_model=n_model, n_seq=n_seq,
                             n_pipe=n_pipe, devices=jax.devices()[:need])
        elif devices is None:
            mesh = make_mesh(n_model=n_model, n_seq=n_seq, n_pipe=n_pipe)
        else:
            mesh = make_mesh(
                n_data=len(devices) // (n_model * n_seq * n_pipe),
                n_model=n_model, n_seq=n_seq, n_pipe=n_pipe, devices=devices,
            )
        n = mesh.shape[DATA_AXIS]
        model_config = dict(model_config or {})
        self.adjust_model_config(model_config, n)
        model_cls = import_model(modelfile, modelclass)
        model = model_cls(model_config)
        recorder = Recorder(
            print_freq=self.config.get("print_freq", 40),
            save_dir=self.config.get("record_dir"),
            verbose=self.config.get("verbose", model.verbose),
        )
        self.trainer = self.make_trainer(model, mesh, recorder)
        self.trainer.compile_iter_fns()
        self.trainer.init_state()
        if self.config.get("resume") or self.config.get("resume_reshard"):
            self.trainer.try_resume()
        return self

    def wait(self):
        """Run training to completion (reference: join the mpirun tree)."""
        if self.trainer is None:
            raise RuntimeError("call init() before wait()")
        tel = self.trainer.telemetry
        try:
            return self.trainer.run()
        finally:
            exc = sys.exc_info()[1]
            if tel is not None and tel.flight is not None and exc is not None:
                # last words BEFORE close(): the flight recorder dumps the
                # event ring + verdicts + fingerprint for any exception
                # escaping training, including the cooperative
                # PreemptionExit (a preempted run's blackbox is its proof
                # of orderly death)
                try:
                    tel.flight.dump(
                        ("preemption" if isinstance(exc, PreemptionExit)
                         else "crash"),
                        health=(tel.health.verdicts()
                                if tel.health is not None else None),
                        error=f"{type(exc).__name__}: {exc}")
                except OSError as e:
                    print(f"blackbox dump failed: {e}", file=sys.stderr)
            if tel is not None:
                # best-effort: a full disk / dead shared mount here (often
                # correlated with whatever killed training) must not mask
                # the primary exception propagating out of run()
                try:
                    tel.close()
                    if jax.process_index() == 0:
                        # rank-0 aggregation: Chrome trace + cross-rank
                        # step-skew / straggler summary over every rank
                        # file visible under the telemetry dir
                        from theanompi_tpu.telemetry import aggregate

                        aggregate.finalize(tel.directory)
                except Exception as e:
                    print(f"telemetry finalize failed: {e}", file=sys.stderr)
