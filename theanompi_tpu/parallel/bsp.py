"""BSP: synchronous data-parallel training (the reference's flagship rule).

Reference (unverified — SURVEY.md §2.1/§3.2): ``theanompi/__init__.py`` class
``BSP`` (``init(devices, modelfile, modelclass)`` composing an mpirun command,
``wait()`` joining) and ``bsp_worker.py`` (per-process train loop: τ=1
exchange of gradients/params per batch via ``BSP_Exchanger``, per-epoch
validation, LR schedule, rank-0 recording).

TPU-native re-expression — no processes, no mpirun: one controller traces a
single train step; ``shard_map`` over the ``data`` mesh axis makes XLA run it
SPMD on every chip with the exchanger's collective compiled *into* the step.
What was "N worker processes each calling train_fn then MPI.Allreduce"
becomes one jitted function whose HLO contains the all-reduce — XLA overlaps
it with the backward pass where dependencies allow, which is the optimization
the reference's exchanger strategies chased by hand.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.exchanger import Exchanger
from theanompi_tpu.parallel.mesh import (
    DATA_AXIS,
    make_mesh,
    replica_rng,
    shard_map,
)
from theanompi_tpu.utils.helper_funcs import import_model, replicate, shard_batch
from theanompi_tpu.utils.recorder import Recorder


def _pmean_floats(tree, axis_name):
    def f(x):
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
            return lax.pmean(x, axis_name)
        return x

    return jax.tree.map(f, tree)


class BSPTrainer:
    """Compiles and drives the BSP step for one model on one mesh.

    Owns the reference worker's ``compile_iter_fns``/``train_iter``/
    ``val_iter`` responsibilities (SURVEY.md §2.3); the model supplies the
    pure functions.
    """

    def __init__(
        self,
        model,
        mesh=None,
        exch_strategy: str = "psum",
        recorder: Recorder | None = None,
        seed: int = 0,
    ):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh(n_data=1)
        self.n_workers = self.mesh.shape[DATA_AXIS]
        self.exchanger = Exchanger(strategy=exch_strategy)
        self.recorder = recorder or Recorder()
        self.seed = seed
        self.optimizer = model.build_optimizer()
        self.global_batch = model.batch_size * self.n_workers
        self._step_fn = None
        self._eval_fn = None
        self.params = None
        self.state = None
        self.opt_state = None
        self.epoch = 0
        self.iteration = 0

    # -- compilation --------------------------------------------------------
    def compile_iter_fns(self) -> None:
        """Build + jit the train/eval steps (reference method name)."""
        model, mesh, ex, opt = self.model, self.mesh, self.exchanger, self.optimizer
        base_key = jax.random.PRNGKey(self.seed)

        def local_step(params, state, opt_state, batch, lr, step):
            rng = replica_rng(jax.random.fold_in(base_key, step), DATA_AXIS)

            def lossw(p):
                return model.loss_fn(p, state, batch, rng, train=True)

            (_, (new_state, metrics)), grads = jax.value_and_grad(
                lossw, has_aux=True
            )(params)
            grads = ex.exchange(grads)
            new_params, new_opt_state = opt.update(grads, opt_state, params, lr)
            metrics = _pmean_floats(metrics, DATA_AXIS)
            # keep non-learned state consistent across replicas (already
            # identical under sync-BN; pmean repairs drift otherwise)
            new_state = _pmean_floats(new_state, DATA_AXIS)
            return new_params, new_state, new_opt_state, metrics

        def local_eval(params, state, batch):
            _, (_, metrics) = model.loss_fn(params, state, batch, None, train=False)
            return _pmean_floats(metrics, DATA_AXIS)

        self._step_fn = jax.jit(
            shard_map(
                local_step,
                self.mesh,
                in_specs=(P(), P(), P(), P(DATA_AXIS), P(), P()),
                out_specs=(P(), P(), P(), P()),
            ),
            donate_argnums=(0, 1, 2),
        )
        self._eval_fn = jax.jit(
            shard_map(
                local_eval,
                self.mesh,
                in_specs=(P(), P(), P(DATA_AXIS)),
                out_specs=P(),
            )
        )

    def init_state(self) -> None:
        params, state = self.model.init_params(jax.random.PRNGKey(self.seed + 1))
        self.params = replicate(self.mesh, params)
        self.state = replicate(self.mesh, state)
        self.opt_state = replicate(self.mesh, self.optimizer.init(params))

    # -- iteration (reference train_iter/val_iter) --------------------------
    def train_iter(self, batch: dict, lr: float, recorder: Recorder | None = None):
        r = recorder or self.recorder
        r.start("wait")
        batch = shard_batch(self.mesh, batch)
        r.end("wait")
        r.start("calc")
        self.params, self.state, self.opt_state, metrics = self._step_fn(
            self.params,
            self.state,
            self.opt_state,
            batch,
            jnp.float32(lr),
            jnp.int32(self.iteration),
        )
        self.iteration += 1
        # fence only at print boundaries: per-iter blocking would serialize
        # the dispatch pipeline (SURVEY.md §7 hard part 5)
        fence = (
            metrics["cost"]
            if self.iteration % r.print_freq == 0
            else None
        )
        r.end("calc", fence=fence)
        r.end_iteration()
        r.train_metrics(**metrics)
        r.print_train_info(self.iteration)
        return metrics

    def val_iter(self, batch: dict, recorder: Recorder | None = None):
        batch = shard_batch(self.mesh, batch)
        return self._eval_fn(self.params, self.state, batch)

    def validate(self, epoch: int):
        # the val set may be smaller than the global batch; shrink to the
        # largest worker-divisible batch rather than silently skipping
        vb = min(self.global_batch, self.model.data.n_val)
        vb -= vb % self.n_workers
        if vb == 0:
            if self.recorder.verbose:
                print(
                    f"validate: n_val={self.model.data.n_val} < "
                    f"{self.n_workers} workers, skipping",
                    flush=True,
                )
            return {}
        accums: dict[str, list] = {}
        for batch in self.model.data.val_batches(vb):
            m = self.val_iter(batch)
            for k, v in m.items():
                accums.setdefault(k, []).append(v)
        means = {k: float(np.mean([float(x) for x in v])) for k, v in accums.items()}
        self.recorder.val_metrics(epoch, **means)
        return means

    # -- full run (reference BSP_Worker.run) --------------------------------
    def run(self):
        if self._step_fn is None:
            self.compile_iter_fns()
        if self.params is None:
            self.init_state()
        model = self.model
        for epoch in range(self.epoch, model.n_epochs):
            self.epoch = epoch
            self.recorder.start_epoch()
            lr = model.adjust_hyperp(epoch)
            for batch in model.data.train_batches(
                self.global_batch, epoch, seed=self.seed
            ):
                self.train_iter(batch, lr)
            self.validate(epoch)
            self.epoch = epoch + 1  # resume point: next epoch, not this one
        self.recorder.save()
        model.cleanup()
        return self.recorder


class BSP:
    """Reference-compatible rule facade.

    Usage (mirrors the reference README pattern, SURVEY.md §3.1)::

        rule = BSP(config={"exch_strategy": "psum"})
        rule.init(devices=8, modelfile="theanompi_tpu.models.wide_resnet",
                  modelclass="WideResNet")
        rule.wait()

    ``devices`` is a worker count, a list of jax devices, or None (all
    devices).  ``init`` builds the mesh and compiles; ``wait`` runs training
    to completion and returns the recorder (there is no process tree to join
    — the "cluster" is the mesh).
    """

    def __init__(self, config: dict[str, Any] | None = None):
        self.config = config or {}
        self.trainer: BSPTrainer | None = None

    def init(
        self,
        devices=None,
        modelfile: str = "theanompi_tpu.models.wide_resnet",
        modelclass: str = "WideResNet",
        model_config: dict | None = None,
    ) -> "BSP":
        if isinstance(devices, int):
            mesh = make_mesh(n_data=devices, devices=jax.devices()[:devices])
        elif devices is None:
            mesh = make_mesh()
        else:
            mesh = make_mesh(n_data=len(devices), devices=devices)
        n = mesh.shape[DATA_AXIS]
        model_config = dict(model_config or {})
        if n > 1:
            # multi-worker: cross-replica BN statistics by default
            model_config.setdefault("bn_axis", DATA_AXIS)
        model_cls = import_model(modelfile, modelclass)
        model = model_cls(model_config)
        self.trainer = BSPTrainer(
            model,
            mesh=mesh,
            exch_strategy=self.config.get("exch_strategy", "psum"),
            recorder=Recorder(
                print_freq=self.config.get("print_freq", 40),
                save_dir=self.config.get("record_dir"),
                verbose=self.config.get("verbose", model.verbose),
            ),
            seed=self.config.get("seed", 0),
        )
        self.trainer.compile_iter_fns()
        self.trainer.init_state()
        return self

    def wait(self):
        """Run training to completion (reference: join the mpirun tree)."""
        if self.trainer is None:
            raise RuntimeError("call init() before wait()")
        return self.trainer.run()
