"""BSP: synchronous data-parallel training (the reference's flagship rule).

Reference (unverified — SURVEY.md §2.1/§3.2): ``theanompi/__init__.py`` class
``BSP`` (``init(devices, modelfile, modelclass)`` composing an mpirun command,
``wait()`` joining) and ``bsp_worker.py`` (per-process train loop: τ=1
exchange of gradients/params per batch via ``BSP_Exchanger``, per-epoch
validation, LR schedule, rank-0 recording).

TPU-native re-expression — no processes, no mpirun: one controller traces a
single train step; ``shard_map`` over the ``data`` mesh axis makes XLA run it
SPMD on every chip with the exchanger's collective compiled *into* the step.
What was "N worker processes each calling train_fn then MPI.Allreduce"
becomes one jitted function whose HLO contains the all-reduce — XLA overlaps
it with the backward pass where dependencies allow, which is the optimization
the reference's exchanger strategies chased by hand.
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.exchanger import BUCKETED_STRATEGIES, Exchanger
from theanompi_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    shard_map,
)
from theanompi_tpu.parallel.overlap import RampSchedule
from theanompi_tpu.parallel.trainer import (
    BaseTrainer,
    Rule,
    make_local_eval,
    make_local_step,
)
from theanompi_tpu.utils.helper_funcs import place


class BSPTrainer(BaseTrainer):
    """Compiles and drives the BSP step for one model on one mesh.

    Owns the reference worker's ``compile_iter_fns``/``train_iter``/
    ``val_iter`` responsibilities (SURVEY.md §2.3); the model supplies the
    pure functions.
    """

    def __init__(self, model, mesh=None, exch_strategy: str = "psum",
                 exch_bucket_mb: float = 4.0, exch_overlap: bool = False,
                 exch_ramp: str | None = None, **kwargs):
        super().__init__(model, mesh=mesh, **kwargs)
        # reduce over every axis the batch is sharded on (data; +seq for
        # sequence-parallel models whose grads are per-shard partials);
        # exch_bucket_mb caps the fused-bucket payload of the *_bucket /
        # ring_int8 / zero1 strategies (see exchanger module docstring);
        # exch_overlap chains per-bucket collectives into backward and
        # exch_ramp schedules coarse->exact wire phases over epochs
        # (both in theanompi_tpu/parallel/overlap.py)
        self.exch_strategy_base = exch_strategy
        self.exch_overlap = bool(exch_overlap)
        self.ramp = (RampSchedule.parse(exch_ramp, exch_strategy)
                     if exch_ramp else None)
        axis_name = model.grad_reduce_axes()
        bucket_bytes = int(float(exch_bucket_mb) * 2**20)

        def build_exchanger(strategy, overlap):
            return Exchanger(strategy=strategy, axis_name=axis_name,
                             bucket_bytes=bucket_bytes, overlap=overlap)

        # every ramp phase's exchanger is built (and therefore validated
        # against the mesh axes) eagerly, so a bad phase fails at trainer
        # construction, not at its epoch boundary mid-run.  Overlap applies
        # to every bucketed phase; a leaf-wise ramp phase has no buckets to
        # chain and runs unchained.  The base strategy must be bucketed for
        # exch_overlap (the Exchanger raises a clear error otherwise).
        self._ramp_exchangers = {
            s: build_exchanger(s, self.exch_overlap
                               and s in BUCKETED_STRATEGIES)
            for s in (self.ramp.strategies if self.ramp else ())
        }
        self.exchanger = self._ramp_exchangers.get(
            exch_strategy) or build_exchanger(exch_strategy, self.exch_overlap)
        if self.checkpointer is not None:
            # ISSUE 8: the elastic reshard planner must recompute the
            # zero1 bucket layout with the exchanger's exact bucket size
            self.checkpointer.bucket_bytes = self.exchanger.bucket_bytes
        self.batch_spec = model.batch_partition()

    def _spec_trees(self):
        """(param_specs, state_specs, opt_specs) from the model's hooks,
        computed shape-only (no device work)."""
        shapes = jax.eval_shape(
            self.model.init_params, jax.random.PRNGKey(self.seed + 1)
        )
        param_t, state_t = shapes
        pspecs = self.model.param_specs(param_t)
        sspecs = self.model.state_specs(state_t)
        if self.exchanger.fuses_update:
            # zero1 stores opt state as flat bucket buffers sharded over
            # the exchange axis — only coherent when params are replicated
            # (pure data parallelism): a tensor/pipeline-sharded leaf holds
            # a different slice per model shard and cannot be packed into
            # one replicated flat bucket.  Specs naming size-1 mesh axes
            # are effectively replicated and fine.
            for spec in jax.tree.leaves(pspecs):
                for entry in (spec or ()):
                    axes = entry if isinstance(entry, tuple) else (entry,)
                    for ax in axes:
                        if ax and self.mesh.shape.get(ax, 1) > 1:
                            raise ValueError(
                                f"exch_strategy 'zero1' requires replicated "
                                f"(data-parallel) params; leaf spec {spec} "
                                f"shards over mesh axis {ax!r} (size "
                                f"{self.mesh.shape[ax]})"
                            )
            ospecs = self.exchanger.zero1_opt_state_specs(
                self.optimizer, param_t, self._exchange_axis_size())
        else:
            ospecs = self.model.opt_state_specs(self.optimizer, pspecs)
        return pspecs, sspecs, ospecs

    # -- compilation ---------------------------------------------------------
    def compile_iter_fns(self) -> None:
        """Build + jit the train/eval steps (reference method name)."""
        self._build_step_fn()
        local_eval = make_local_eval(self.model, axes=self.exchanger.axis_name)
        pspecs, sspecs, _ = self._spec_trees()
        self._eval_fn = jax.jit(
            shard_map(
                local_eval,
                self.mesh,
                in_specs=(pspecs, sspecs, self.batch_spec),
                out_specs=P(),
            )
        )

    def _build_step_fn(self) -> None:
        """(Re)build the jitted train step around the ACTIVE exchanger —
        split out of :meth:`compile_iter_fns` so an ``exch_ramp`` phase
        switch rebuilds only the step (the eval fn doesn't touch the
        exchange and would recompile for nothing)."""
        pspecs, sspecs, ospecs = self._spec_trees()
        sentinel_skip = self.sentinel is not None and self.sentinel.device_guard
        if sentinel_skip:
            # the guard's finite-indicator psums over the EXCHANGE axes
            # only; a sharded model/seq/pipe axis outside them could leave
            # shards selecting different branches — refuse rather than
            # silently diverge
            exch_axes = (self.exchanger.axis_name
                         if isinstance(self.exchanger.axis_name, tuple)
                         else (self.exchanger.axis_name,))
            for axis in (MODEL_AXIS, SEQ_AXIS, PIPE_AXIS):
                if self.mesh.shape.get(axis, 1) > 1 and axis not in exch_axes:
                    raise ValueError(
                        f"sentinel_policy 'skip_batch' is data-parallel "
                        f"only: mesh axis {axis!r} has size "
                        f"{self.mesh.shape[axis]} outside the exchange axes "
                        f"{exch_axes} (use 'abort' or 'rollback')"
                    )
        local_step = make_local_step(
            self.model, self.optimizer, jax.random.PRNGKey(self.seed),
            exchanger=self.exchanger, param_specs=pspecs,
            sentinel_skip=sentinel_skip,
        )

        from contextlib import nullcontext
        span = (self.telemetry.span("exchange.overlap",
                                    strategy=self.exchanger.strategy)
                if self.telemetry is not None and self.exchanger.overlap
                else nullcontext())
        with span:
            # the span marks (re)arming of the chained step — the overlap
            # itself is inside the compiled program and host-invisible
            self._step_fn = jax.jit(
                shard_map(
                    local_step,
                    self.mesh,
                    in_specs=(pspecs, sspecs, ospecs, self.batch_spec,
                              P(), P()),
                    out_specs=(pspecs, sspecs, ospecs, P()),
                ),
                # 5 is the device step counter: donated so the returned
                # `_next_step` scalar aliases it (trainer scalar-hoisting)
                donate_argnums=(0, 1, 2, 5),
            )

    # -- quantization ramp (exch_ramp) ---------------------------------------
    def _maybe_ramp(self, epoch: int) -> None:
        """Activate the ramp phase ``epoch`` dictates (epoch-boundary hook).

        A switch swaps in the phase's pre-validated exchanger, rebuilds
        ONLY the step fn (one fenced recompile per phase — jit compiles
        lazily, so phases that never run never compile), and invalidates
        the wire-byte cache so telemetry's ``exchange.accounting`` instant
        re-emits with the phase's strategy/bytes.  The phase is a pure
        function of the absolute epoch, so ``try_resume`` -> ``_run_epochs``
        lands a mid-ramp restart in the right phase with no extra state.
        """
        if self.ramp is None:
            return
        want = self.ramp.strategy_for_epoch(epoch)
        if want == self.exchanger.strategy:
            return
        self.exchanger = self._ramp_exchangers[want]
        self._build_step_fn()
        self._compiled_step_cache = None
        self._exchange_wire_bytes_cached = None
        if self.telemetry is not None:
            phase = self.ramp.phase_for_epoch(epoch)
            self.telemetry.gauge("exchange.ramp_phase", phase, epoch=epoch)
            self.telemetry.instant("exchange.ramp_switch", epoch=epoch,
                                   strategy=want, phase=phase)

    def _fingerprint_extra(self) -> dict:
        """Ramp-proof the run fingerprint: stamp the BASE strategy (the
        active exchanger varies by epoch under a ramp, and a resume
        compares fingerprints before the first ``_maybe_ramp``), plus the
        ramp/overlap knobs themselves when set — changing either across a
        resume is a real topology change (different wire numerics /
        schedule) and should hit the ``resume_force`` gate."""
        extra = {}
        if self.ramp is not None:
            extra["exchange"] = self.exch_strategy_base
            extra["exch_ramp"] = self.ramp.describe()
        if self.exch_overlap:
            extra["exch_overlap"] = True
        return extra

    def init_state(self) -> None:
        params, state = self.model.init_params(jax.random.PRNGKey(self.seed + 1))
        pspecs, sspecs, ospecs = self._spec_trees()
        self.params = place(self.mesh, params, pspecs)
        self.state = place(self.mesh, state, sspecs)
        if self.exchanger.fuses_update:
            # ZeRO-1: flat bucket buffers, sharded 1/n per device by ospecs
            opt_state = self.exchanger.zero1_init_opt_state(
                self.optimizer, params, self._exchange_axis_size())
        else:
            opt_state = self.model.init_opt_state(self.optimizer, params)
        self.opt_state = place(self.mesh, opt_state, ospecs)


class BSP(Rule):
    """Synchronous data-parallel rule (see :class:`Rule` for usage)."""

    def adjust_model_config(self, model_config: dict, n_workers: int) -> None:
        if n_workers > 1:
            # multi-worker: cross-replica BN statistics by default
            model_config.setdefault("bn_axis", DATA_AXIS)

    def make_trainer(self, model, mesh, recorder) -> BSPTrainer:
        return BSPTrainer(
            model,
            mesh=mesh,
            exch_strategy=self.config.get("exch_strategy", "psum"),
            exch_bucket_mb=self.config.get("exch_bucket_mb", 4.0),
            exch_overlap=bool(self.config.get("exch_overlap", False)),
            exch_ramp=self.config.get("exch_ramp") or None,
            **self.common_trainer_kwargs(recorder),
        )
