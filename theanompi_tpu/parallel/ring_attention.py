"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

Beyond the reference's capability set (a 2016 framework has no attention at
all — SURVEY.md §5 "long-context: absent"), but first-class here: long-context
training is part of this framework's scale contract, and the communication
shape is exactly the exchanger's ring (``theanompi_tpu.parallel.exchanger``)
applied to keys/values instead of gradients.

Mechanism (Liu et al. 2023, "Ring Attention with Blockwise Transformers"):
shard the sequence over the ``seq`` axis; each device keeps its Q block
resident and circulates KV blocks around the ICI ring with ``ppermute``,
accumulating attention with an online (flash-style) softmax, so the full
S×S score matrix never materializes and per-device memory is O(S/n · d).
Causal masking uses global block offsets: whole KV-future blocks are skipped
numerically (their contribution is masked), intra-block masking applies on
the diagonal block.

All functions are pure and run inside ``shard_map``; XLA overlaps each
ppermute hop with the current block's compute where dependencies allow.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.parallel.mesh import SEQ_AXIS

_NEG_INF = -1e30  # fp32-safe mask value (finite: avoids NaN from inf-inf)


def _block_attend(q, k, v, m_prev, l_prev, acc, mask=None):
    """One online-softmax accumulation step.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; running max ``m_prev`` [B, H, Tq],
    normalizer ``l_prev`` [B, H, Tq], accumulator ``acc`` [B, Tq, H, D].
    """
    scale = q.shape[-1] ** -0.5
    # scores: [B, H, Tq, Tk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    # renormalize previous accumulation to the new max
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[..., None])  # [B, H, Tq, Tk]
    if mask is not None:
        # a fully-masked row while m is still at the -1e30 init would give
        # p = exp(s - m_new) = exp(0) = 1 per entry — bogus mass.  Zeroing
        # masked positions makes accumulation order-independent (no
        # "diagonal block first" invariant needed); XLA fuses the select.
        p = jnp.where(mask, p, 0.0)
    l_new = l_prev * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    acc_new = acc * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def blockwise_attention(q, k, v, causal: bool = False, block_size: int | None = None):
    """Single-device flash-style attention (the ring's n=1 case / reference
    implementation for tests).  [B, T, H, D] layout."""
    b, t, h, d = q.shape
    if block_size is None or block_size >= k.shape[1]:
        blocks = [(0, k.shape[1])]
    else:
        blocks = [
            (i, min(i + block_size, k.shape[1]))
            for i in range(0, k.shape[1], block_size)
        ]
    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, t), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)
    acc = jnp.zeros((b, t, h, d), jnp.float32)
    q_pos = jnp.arange(t)
    for start, stop in blocks:
        kb = k[:, start:stop].astype(jnp.float32)
        vb = v[:, start:stop]
        mask = None
        if causal:
            mask = q_pos[:, None] >= jnp.arange(start, stop)[None, :]
            mask = mask[None, None]
        m, l, acc = _block_attend(qf, kb, vb, m, l, acc, mask)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_mask(me, src, t):
    """Causal mask tile for local q rows vs the KV block that originated at
    ``src`` (global block offsets): [1, 1, t, t]."""
    q_pos = me * t + jnp.arange(t)
    k_pos = src * t + jnp.arange(t)
    return (q_pos[:, None] >= k_pos[None, :])[None, None]


def _ring_forward(q, k, v, causal, axis_name):
    """The KV-circulating forward; -> (out [B,T,H,D], lse [B,H,T])."""
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, t, h, d = q.shape

    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, t), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)
    acc = jnp.zeros((b, t, h, d), jnp.float32)
    ring = [(i, (i + 1) % n) for i in range(n)]

    kv = (k, v)
    for hop in range(n):
        # after `hop` forwards along the ring, we hold the block that
        # originated at (me - hop) mod n
        src = (me - hop) % n
        kb, vb = kv
        mask = _ring_mask(me, src, t) if causal else None
        m, l, acc = _block_attend(
            qf, kb.astype(jnp.float32), vb, m, l, acc, mask
        )
        if hop < n - 1:
            kv = jax.tree.map(lambda x: lax.ppermute(x, axis_name, ring), kv)

    # fully-masked rows (can't happen with causal self-attention since the
    # diagonal is always visible, but guard the division anyway)
    l_safe = jnp.maximum(l, 1e-30)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype), m + jnp.log(l_safe)


def ring_attention(q, k, v, causal: bool = False, axis_name: str = SEQ_AXIS):
    """Sequence-parallel attention inside ``shard_map`` over ``axis_name``.

    q/k/v: the LOCAL sequence shard, [B, T_local, H, D].  Equivalent to full
    attention over the gathered sequence (see tests), with KV circulating the
    ring instead of being gathered.

    The backward is a custom second ring pass (Liu et al. 2023 §3): plain
    autodiff of the forward would save every hop's [T_local, T_local]
    probability block — O(T²/n) per device, the exact thing ring attention
    exists to avoid.  Instead the VJP recomputes probabilities per hop from
    the saved (q, k, v, out, lse) and circulates a (k, v, dk, dv) bundle a
    full lap, so each shard's dk/dv accumulate contributions from every
    query shard and arrive back home; residual memory stays O(T·d/n).
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return blockwise_attention(q, k, v, causal=causal)
    return _ring_flash(q, k, v, causal, axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash(q, k, v, causal, axis_name):
    out, _ = _ring_forward(q, k, v, causal, axis_name)
    return out


def _ring_flash_fwd(q, k, v, causal, axis_name):
    out, lse = _ring_forward(q, k, v, causal, axis_name)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(causal, axis_name, res, g):
    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = d ** -0.5
    ring = [(i, (i + 1) % n) for i in range(n)]

    qf = q.astype(jnp.float32)
    do = g.astype(jnp.float32)
    # delta_i = sum_d dO_i * O_i : [B, H, T] (lse's layout)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1).transpose(0, 2, 1)

    dq = jnp.zeros((b, t, h, d), jnp.float32)
    bundle = (k, v,
              jnp.zeros((b, t, h, d), jnp.float32),
              jnp.zeros((b, t, h, d), jnp.float32))
    for hop in range(n):
        src = (me - hop) % n
        kb, vb, dkb, dvb = bundle
        kbf, vbf = kb.astype(jnp.float32), vb.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kbf,
                       preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse[..., None])
        if causal:
            p = jnp.where(_ring_mask(me, src, t), p, 0.0)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do, vbf,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, kbf)
        dkb = dkb + jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        dvb = dvb + jnp.einsum("bhqk,bqhd->bkhd", p, do)
        # permute after EVERY hop (n total): each KV block visits all query
        # shards and its accumulated dk/dv land back on its home shard
        bundle = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, ring), (kb, vb, dkb, dvb)
        )
    _, _, dk, dv = bundle
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)
