"""Tensor parallelism: Megatron-style partitioned layers + partition rules.

Beyond the reference's capability set (SURVEY.md §2.4: data parallelism only)
but first-class here: the ``model`` mesh axis exists from day one so TP
composes with the rules.  The scheme is the standard pair:

- **column-parallel**: weight ``[D, F]`` sharded on F — no communication in
  the forward; outputs (and bias) are feature-sharded;
- **row-parallel**: weight ``[F, D]`` sharded on F — consumes feature-sharded
  inputs, produces partial sums, one ``psum`` over ``model`` completes the
  matmul (bias added after, once).

Layers run inside the rule's ``shard_map``; the *same* layer code runs
unsharded too (plain jit, tests) because the Megatron ``f``/``g`` collective
operators below degrade to identity when the axis is absent.  Parameter
placement comes from path-regex partition rules (the t5x/flax convention)
rather than per-layer plumbing.
"""

from __future__ import annotations

import dataclasses
import re
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.ops import layers as L
from theanompi_tpu.ops import quant
from theanompi_tpu.parallel.mesh import MODEL_AXIS


def axis_bound(axis_name: str) -> bool:
    """Is ``axis_name`` bound in the current collective context?"""
    try:
        lax.axis_size(axis_name)
        return True
    except NameError:
        return False


# -- Megatron f/g collectives with pinned gradients ---------------------------
#
# Under shard_map(check_vma=False) the default transpose of ``psum`` does not
# give the gradients tensor parallelism needs: the cotangent entering a
# column-parallel matmul covers only that shard's feature slice, so the grads
# of everything upstream (embeddings, LayerNorms) come out as per-shard
# partials that silently diverge across model shards.  The standard fix
# (Megatron-LM's f/g operators) pins both directions with custom VJPs:
#
# - ``g`` (row-parallel output): forward all-reduce; backward identity —
#   the output cotangent is replicated and is exactly the cotangent of each
#   shard's partial sum.
# - ``f`` (column-parallel input): forward identity; backward all-reduce —
#   each shard's input cotangent is the partial from its feature slice; the
#   true cotangent is their sum.
#
# Both degrade to identity when the axis is unbound (plain jit) or size 1,
# so the same layer code runs unsharded too.

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _g_op(x, axis_name: str):
    return lax.psum(x, axis_name)


def _g_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _g_bwd(axis_name, _, ct):
    return (ct,)


_g_op.defvjp(_g_fwd, _g_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _f_op(x, axis_name: str):
    return x


def _f_fwd(x, axis_name):
    return x, None


def _f_bwd(axis_name, _, ct):
    return (lax.psum(ct, axis_name),)


_f_op.defvjp(_f_fwd, _f_bwd)


def psum_fwd_identity_bwd(x, axis_name: str = MODEL_AXIS):
    """Megatron ``g``: all-reduce in forward, pass-through in backward."""
    if axis_bound(axis_name) and lax.axis_size(axis_name) > 1:
        return _g_op(x, axis_name)
    return x


def identity_fwd_psum_bwd(x, axis_name: str = MODEL_AXIS):
    """Megatron ``f``: pass-through in forward, all-reduce in backward."""
    if axis_bound(axis_name) and lax.axis_size(axis_name) > 1:
        return _f_op(x, axis_name)
    return x


@dataclasses.dataclass(frozen=True)
class ColumnParallelDense(L.Dense):
    """Feature-sharded Dense: w ``P(None, model)``, b ``P(model)``.

    Forward is communication-free (the replicated input is consumed as-is);
    backward all-reduces the input cotangent (Megatron ``f`` — each shard
    only produces the partial from its feature slice).  ``input_synced=True``
    skips the ``f`` operator when the caller already applied it to a shared
    input (e.g. attention applies it once for q/k/v instead of three times).
    init sees the GLOBAL width (the host builds full params; the trainer
    places shards per the partition rules).
    """

    input_synced: bool = False

    @property
    def name(self) -> str:
        return "cpdense"

    def apply(self, params, state, x, *, train=False, rng=None):
        if not self.input_synced:
            x = identity_fwd_psum_bwd(x, MODEL_AXIS)
        return super().apply(params, state, x, train=train, rng=rng)


class RowParallelDense(L.Dense):
    """Reduction-sharded Dense: w ``P(model, None)``; psum completes the sum.

    The psum is the Megatron ``g`` operator (backward = identity: the output
    cotangent is replicated and already is the cotangent of each shard's
    partial sum).  The bias is added after the psum (adding before would
    apply it ``model``-many times).
    """

    @property
    def name(self) -> str:
        return "rpdense"

    def apply(self, params, state, x, *, train=False, rng=None):
        y = quant.matmul_any(x, params["w"])
        y = psum_fwd_identity_bwd(y, MODEL_AXIS)
        if self.use_bias:
            y = y + params["b"].astype(x.dtype)
        return y, state


#: path-regex -> PartitionSpec; first match wins (order matters).
#: Covers both Sequential-auto-named layers (``03_cpdense/w``) and the
#: fixed keys composite layers use (attention ``q/k/v/o``, MLP ``up/down``).
TP_RULES: tuple[tuple[str, P], ...] = (
    (r".*cpdense.*/w$", P(None, MODEL_AXIS)),
    (r".*cpdense.*/b$", P(MODEL_AXIS)),
    (r".*rpdense.*/w$", P(MODEL_AXIS, None)),
    (r".*/attn/[qkv]/w$", P(None, MODEL_AXIS)),
    (r".*/attn/[qkv]/b$", P(MODEL_AXIS)),
    (r".*/attn/o/w$", P(MODEL_AXIS, None)),
    (r".*/up/w$", P(None, MODEL_AXIS)),
    (r".*/up/b$", P(MODEL_AXIS)),
    (r".*/down/w$", P(MODEL_AXIS, None)),
)


def specs_from_rules(params, rules=TP_RULES, default: P = P()):
    """Map each param leaf's key path against ``rules``; unmatched -> default.

    Paths are ``"/"``-joined dict keys/indices, e.g.
    ``"net/03_cpdense/w"`` — the same naming ``Sequential.init`` produces.
    """

    def spec_for(path, leaf):
        del leaf
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        for pattern, spec in rules:
            if re.fullmatch(pattern, key):
                return spec
        return default

    return jax.tree_util.tree_map_with_path(spec_for, params)


def check_divisible(params, specs, mesh) -> None:
    """Fail fast if a rule shards a dim that doesn't divide the axis size."""
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    for (path, leaf), spec in zip(flat_p, flat_s):
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            size = mesh.shape[axis]
            if leaf.shape[dim] % size != 0:
                key = "/".join(str(getattr(p, "key", p)) for p in path)
                raise ValueError(
                    f"param {key!r} dim {dim} ({leaf.shape[dim]}) not "
                    f"divisible by mesh axis {axis!r} ({size})"
                )
