"""Tensor parallelism: Megatron-style partitioned layers + partition rules.

Beyond the reference's capability set (SURVEY.md §2.4: data parallelism only)
but first-class here: the ``model`` mesh axis exists from day one so TP
composes with the rules.  The scheme is the standard pair:

- **column-parallel**: weight ``[D, F]`` sharded on F — no communication in
  the forward; outputs (and bias) are feature-sharded;
- **row-parallel**: weight ``[F, D]`` sharded on F — consumes feature-sharded
  inputs, produces partial sums, one ``psum`` over ``model`` completes the
  matmul (bias added after, once).

Layers run inside the rule's ``shard_map``; the *same* layer code runs
unsharded too (plain jit, tests) because ``maybe_psum`` degrades to identity
when the axis is absent.  Parameter placement comes from path-regex partition
rules (the t5x/flax convention) rather than per-layer plumbing.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.ops import layers as L
from theanompi_tpu.parallel.mesh import MODEL_AXIS


def axis_bound(axis_name: str) -> bool:
    """Is ``axis_name`` bound in the current collective context?"""
    try:
        lax.axis_size(axis_name)
        return True
    except NameError:
        return False


def maybe_psum(x, axis_name: str = MODEL_AXIS):
    """psum over ``axis_name`` if bound (shard_map), else identity (plain jit)."""
    if axis_bound(axis_name):
        return lax.psum(x, axis_name)
    return x


class ColumnParallelDense(L.Dense):
    """Feature-sharded Dense: w ``P(None, model)``, b ``P(model)``.

    Forward is communication-free; init sees the GLOBAL width (the host
    builds full params; the trainer places shards per the partition rules).
    """

    @property
    def name(self) -> str:
        return "cpdense"


class RowParallelDense(L.Dense):
    """Reduction-sharded Dense: w ``P(model, None)``; psum completes the sum.

    The bias is added after the psum (adding before would apply it
    ``model``-many times).
    """

    @property
    def name(self) -> str:
        return "rpdense"

    def apply(self, params, state, x, *, train=False, rng=None):
        y = x @ params["w"].astype(x.dtype)
        y = maybe_psum(y, MODEL_AXIS)
        if self.use_bias:
            y = y + params["b"].astype(x.dtype)
        return y, state


#: path-regex -> PartitionSpec; first match wins (order matters).
#: Covers both Sequential-auto-named layers (``03_cpdense/w``) and the
#: fixed keys composite layers use (attention ``q/k/v/o``, MLP ``up/down``).
TP_RULES: tuple[tuple[str, P], ...] = (
    (r".*cpdense.*/w$", P(None, MODEL_AXIS)),
    (r".*cpdense.*/b$", P(MODEL_AXIS)),
    (r".*rpdense.*/w$", P(MODEL_AXIS, None)),
    (r".*/attn/[qkv]/w$", P(None, MODEL_AXIS)),
    (r".*/attn/[qkv]/b$", P(MODEL_AXIS)),
    (r".*/attn/o/w$", P(MODEL_AXIS, None)),
    (r".*/up/w$", P(None, MODEL_AXIS)),
    (r".*/up/b$", P(MODEL_AXIS)),
    (r".*/down/w$", P(MODEL_AXIS, None)),
)


def specs_from_rules(params, rules=TP_RULES, default: P = P()):
    """Map each param leaf's key path against ``rules``; unmatched -> default.

    Paths are ``"/"``-joined dict keys/indices, e.g.
    ``"net/03_cpdense/w"`` — the same naming ``Sequential.init`` produces.
    """

    def spec_for(path, leaf):
        del leaf
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        for pattern, spec in rules:
            if re.fullmatch(pattern, key):
                return spec
        return default

    return jax.tree_util.tree_map_with_path(spec_for, params)


def check_divisible(params, specs, mesh) -> None:
    """Fail fast if a rule shards a dim that doesn't divide the axis size."""
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    for (path, leaf), spec in zip(flat_p, flat_s):
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            size = mesh.shape[axis]
            if leaf.shape[dim] % size != 0:
                key = "/".join(str(getattr(p, "key", p)) for p in path)
                raise ValueError(
                    f"param {key!r} dim {dim} ({leaf.shape[dim]}) not "
                    f"divisible by mesh axis {axis!r} ({size})"
                )
