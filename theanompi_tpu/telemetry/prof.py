"""``tmprof`` — step-time attribution tables + the perf ledger (ISSUE 16).

Attribution mode (the default) re-derives the segment decomposition from
a telemetry directory's event files — the same numbers the in-process
:class:`~theanompi_tpu.telemetry.profile.StepAttributor` publishes to
``ATTRIB.json``, recomputed offline so the tool works on any recorded
run::

    tmprof ./telemetry                  # attribution table per rank
    tmprof ./telemetry --json           # machine-readable
    tmprof ./telemetry --write          # also (re)publish ATTRIB.json

Ledger mode drives ``PERF_LEDGER.jsonl`` (``telemetry/ledger.py``)::

    tmprof --ledger update BENCH_r06.json SERVE.json
    tmprof --ledger check               # exit 1 on any regression
    tmprof --ledger backfill .          # one-shot ingest of repo artifacts
    tmprof --ledger show                # per-metric trajectories

Exit contract (shared with ``tmhealth``/``tmlint`` — a read-mostly
reporting tool, not a party to the supervisor's 70/75–79 codes): ``0``
clean, ``1`` at least one problem (a regression verdict in ``--ledger
check``; an attribution whose unattributed host share exceeds half the
window — the stream is missing its spans), ``2`` usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from theanompi_tpu.telemetry.ledger import (
    DEFAULT_TOLERANCE,
    DEFAULT_WINDOW,
    LEDGER_FILENAME,
    PerfLedger,
    read_ledger,
    regressions,
    trajectories,
)
from theanompi_tpu.telemetry.profile import (
    ATTRIB_FILENAME,
    attribute_events,
    format_attribution,
)

#: attribution-mode problem threshold: a majority-unattributed window
#: means the run's spans never made it into the stream
HOST_SHARE_LIMIT = 0.5


def _attribution(args) -> int:
    from theanompi_tpu.telemetry.aggregate import load_all_events

    if not os.path.isdir(args.directory):
        print(f"tmprof: error: no such directory: {args.directory}",
              file=sys.stderr)
        return 2
    events = load_all_events(args.directory)
    per_rank = attribute_events(events) if events else {}
    if not per_rank:
        # a finished run may have rotated its events away; the published
        # summary is then the only witness
        from theanompi_tpu.telemetry.profile import read_attrib

        attrib = read_attrib(args.directory)
        if attrib:
            per_rank = attrib.get("per_rank", {})
    if not per_rank:
        print(f"tmprof: error: no attributable events or "
              f"{ATTRIB_FILENAME} in {args.directory}", file=sys.stderr)
        return 2
    if args.write:
        payload = {"per_rank": per_rank}
        path = os.path.join(args.directory, ATTRIB_FILENAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    if args.as_json:
        print(json.dumps({"per_rank": per_rank}, indent=1))
    else:
        print(format_attribution(per_rank))
    worst_host = max((res["segments"].get("host", {}).get("share", 0.0)
                      for res in per_rank.values()), default=0.0)
    return 1 if worst_host > HOST_SHARE_LIMIT else 0


def _ledger(args) -> int:
    ledger = PerfLedger(args.ledger_path)
    if args.ledger == "update":
        paths = args.paths or ([args.directory] if args.directory else [])
        if not paths:
            print("tmprof: error: --ledger update needs artifact paths",
                  file=sys.stderr)
            return 2
        written = []
        for p in paths:
            if not os.path.exists(p):
                print(f"tmprof: error: no such artifact: {p}",
                      file=sys.stderr)
                return 2
            written.extend(ledger.ingest_artifact(p))
        ledger.snapshot(tolerance=args.tolerance)
        print(f"ingested {len(written)} new record(s) into "
              f"{args.ledger_path}")
        return 0
    if args.ledger == "backfill":
        root = args.directory or "."
        if not os.path.isdir(root):
            print(f"tmprof: error: no such directory: {root}",
                  file=sys.stderr)
            return 2
        written = ledger.backfill(root)
        ledger.snapshot(tolerance=args.tolerance)
        print(f"backfilled {len(written)} record(s) from {root} into "
              f"{args.ledger_path}")
        return 0
    records = read_ledger(args.ledger_path)
    if not records:
        print(f"tmprof: error: no ledger at {args.ledger_path}",
              file=sys.stderr)
        return 2
    if args.ledger == "show":
        if args.as_json:
            print(json.dumps(trajectories(records), indent=1))
        else:
            for metric, pts in sorted(trajectories(records).items()):
                vals = " -> ".join(f"{p['value']:g}" for p in pts[-6:])
                print(f"{metric:<48} [{len(pts)}] {vals}")
        return 0
    # check
    verdicts = ledger.check(tolerance=args.tolerance, window=args.window)
    bad = regressions(verdicts)
    if args.as_json:
        print(json.dumps({"verdicts": verdicts}, indent=1))
    else:
        for v in verdicts:
            if v["verdict"] == "insufficient_history" and not args.verbose:
                continue
            mark = {"ok": " ", "improvement": "+",
                    "regression": "X"}.get(v["verdict"], "?")
            delta = ("" if v["delta_pct"] is None
                     else f"  {v['delta_pct']:+.1f}% vs median "
                          f"{v['baseline']:g} (tol "
                          f"{v['tolerance_pct']:g}%)")
            print(f"[{mark}] {v['verdict']:<12} {v['metric']:<48} "
                  f"latest {v['latest']:g}{delta}")
        n_skip = sum(1 for v in verdicts
                     if v["verdict"] == "insufficient_history")
        if n_skip and not args.verbose:
            print(f"({n_skip} single-point metric(s) without history "
                  f"omitted; --verbose shows them)")
    return 1 if bad else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tmprof",
        description="Step-time attribution tables from a telemetry dir, "
                    "and the PERF_LEDGER.jsonl regression trajectory")
    p.add_argument("directory", nargs="?",
                   help="telemetry dir (attribution mode) or repo dir "
                        "(--ledger backfill)")
    p.add_argument("--ledger", choices=("update", "check", "backfill",
                                        "show"),
                   help="drive the perf ledger instead of attributing")
    p.add_argument("paths", nargs="*",
                   help="artifact JSONs for --ledger update")
    p.add_argument("--ledger-path", default=LEDGER_FILENAME,
                   help=f"ledger file (default ./{LEDGER_FILENAME})")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="relative regression tolerance (default 0.10)")
    p.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                   help="trailing-median window (default 5)")
    p.add_argument("--write", action="store_true",
                   help="attribution mode: also publish ATTRIB.json")
    p.add_argument("--verbose", action="store_true",
                   help="--ledger check: include single-point metrics")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    args = p.parse_args(argv)

    if args.ledger:
        return _ledger(args)
    if not args.directory:
        p.print_usage(sys.stderr)
        print("tmprof: error: a telemetry directory is required "
              "(or --ledger MODE)", file=sys.stderr)
        return 2
    return _attribution(args)


if __name__ == "__main__":
    sys.exit(main())
