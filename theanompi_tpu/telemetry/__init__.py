"""Unified telemetry layer (ISSUE 1): structured spans, collective byte
accounting, live training metrics.

Three fragments existed before this package — the Recorder's host splits,
the bounded ``jax.profiler`` window, and per-round bench JSON — none of
which emitted structured events.  This package is the common substrate:

- :class:`~theanompi_tpu.telemetry.core.Telemetry` — per-rank JSONL event
  sink (spans / counters / gauges, monotonic timestamps, rank+host tags,
  bounded rotation) with a metrics registry flushed at ``print_freq``;
- :mod:`~theanompi_tpu.telemetry.chrome_trace` — export to the Chrome
  trace-event format so host-side spans render in Perfetto alongside the
  ``profile_dir`` device traces;
- :mod:`~theanompi_tpu.telemetry.aggregate` — rank-0 merge + cross-rank
  step-skew / straggler summary for the multihost path;
- :mod:`~theanompi_tpu.telemetry.health` — streaming health detectors
  (hang, straggler skew, loss spike/NaN, throughput regression,
  checkpoint stall, serving SLO) publishing typed verdicts to
  ``HEALTH.json`` (ISSUE 13);
- :mod:`~theanompi_tpu.telemetry.flight_recorder` — bounded in-memory
  event ring dumped as ``blackbox.json`` on crash/SIGTERM;
- :mod:`~theanompi_tpu.telemetry.profile` — streaming step-time
  attribution (data/compute/comm/validate/checkpoint/host for training,
  queue-wait/prefill/decode/rollout-swap for serving) publishing
  ``attr.*`` gauges, per-device HBM watermarks, and ``ATTRIB.json``
  (ISSUE 16);
- :mod:`~theanompi_tpu.telemetry.ledger` — the append-only
  ``PERF_LEDGER.jsonl`` cross-run perf trajectory with typed regression
  verdicts (ISSUE 16);
- :mod:`~theanompi_tpu.telemetry.cli` / ``.prof`` — the ``tmhealth`` and
  ``tmprof`` CLIs (``python -m theanompi_tpu.telemetry``).

Everything is off by default: the trainer holds ``telemetry=None`` unless
a sink was configured (``telemetry_dir`` rule config / ``--telemetry-dir``
launcher flag), and every integration point guards on that, so a disabled
run makes zero telemetry calls on the hot path.
"""

from theanompi_tpu.telemetry.core import Span, Telemetry
from theanompi_tpu.telemetry.flight_recorder import (
    FlightRecorder,
    read_blackbox,
)
from theanompi_tpu.telemetry.health import (
    HealthConfig,
    HealthMonitor,
    hung_verdict,
    read_health,
    replay_events,
)
from theanompi_tpu.telemetry.ledger import (
    PerfLedger,
    check_ledger,
    read_ledger,
)
from theanompi_tpu.telemetry.metrics import (
    MetricsRegistry,
    device_memory_stats,
    mfu,
    peak_flops,
    per_device_memory_stats,
    step_flops_estimate,
)
from theanompi_tpu.telemetry.profile import (
    StepAttributor,
    attribute_events,
    parse_profile_window,
    read_attrib,
)
from theanompi_tpu.telemetry.sink import (
    EventSink,
    read_events,
    sink_files,
    tail_events,
)

__all__ = [
    "EventSink",
    "FlightRecorder",
    "HealthConfig",
    "HealthMonitor",
    "MetricsRegistry",
    "PerfLedger",
    "Span",
    "StepAttributor",
    "Telemetry",
    "attribute_events",
    "check_ledger",
    "device_memory_stats",
    "hung_verdict",
    "mfu",
    "parse_profile_window",
    "peak_flops",
    "per_device_memory_stats",
    "read_attrib",
    "read_blackbox",
    "read_events",
    "read_health",
    "read_ledger",
    "replay_events",
    "sink_files",
    "step_flops_estimate",
    "tail_events",
]
