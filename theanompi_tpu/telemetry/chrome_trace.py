"""Chrome trace-event exporter: telemetry JSONL -> Perfetto-loadable JSON.

Spans become complete (``ph: "X"``) events on a per-rank process track
(``pid`` = rank, ``tid`` = emitting thread), counters become ``ph: "C"``
counter tracks, instants become ``ph: "i"``.  Load the output at
ui.perfetto.dev (or chrome://tracing) next to the ``profile_dir`` device
trace: the host-side wait/calc/comm spans line up with the XLA device
timeline, which is the whole point — one picture of where the step went.

Timestamps: trace-event ``ts`` is microseconds.  Each rank's perf_counter
epoch is arbitrary, so ranks are normalized independently to their own
first event — tracks align at session start, and cross-rank *duration*
comparisons (the skew summary in ``aggregate.py``) stay exact while
cross-rank simultaneity is approximate, as it must be without a fleet
clock.
"""

from __future__ import annotations

import json
import os

from theanompi_tpu.telemetry.sink import read_events


def to_trace_events(events: list[dict]) -> list[dict]:
    """Convert one or more ranks' telemetry events to trace-event dicts."""
    t0_by_rank: dict[int, float] = {}
    for ev in events:
        r = ev.get("rank", 0)
        t0_by_rank[r] = min(t0_by_rank.get(r, float("inf")), ev["ts"])

    out = []
    for ev in events:
        rank = ev.get("rank", 0)
        us = (ev["ts"] - t0_by_rank[rank]) * 1e6
        kind = ev.get("kind")
        args = {k: v for k, v in ev.items()
                if k not in ("ts", "kind", "name", "rank", "dur", "tid")}
        if kind == "span":
            out.append({"ph": "X", "name": ev["name"], "pid": rank,
                        "tid": ev.get("tid", 0), "ts": us,
                        "dur": ev["dur"] * 1e6, "args": args})
        elif kind in ("counter", "gauge"):
            out.append({"ph": "C", "name": ev["name"], "pid": rank,
                        "ts": us,
                        "args": {ev["name"]: ev.get("total",
                                                    ev.get("value", 0))}})
        elif kind in ("instant", "metrics", "meta"):
            out.append({"ph": "i", "name": ev["name"], "pid": rank,
                        "tid": ev.get("tid", 0), "ts": us, "s": "p",
                        "args": args})
    out.sort(key=lambda e: e["ts"])
    return out


def write_chrome_trace(events: list[dict], out_path: str) -> str:
    """Write already-loaded telemetry events as Chrome trace JSON; -> path."""
    trace = {
        "traceEvents": to_trace_events(events),
        "displayTimeUnit": "ms",
        "otherData": {"source": "theanompi_tpu.telemetry"},
    }
    with open(out_path + ".tmp", "w") as f:
        json.dump(trace, f)
    os.replace(out_path + ".tmp", out_path)
    return out_path


def export_chrome_trace(jsonl_paths: list[str], out_path: str) -> str:
    """Read telemetry JSONL files, write one Chrome trace JSON; -> path."""
    events: list[dict] = []
    for p in jsonl_paths:
        events.extend(read_events(p))
    return write_chrome_trace(events, out_path)
