"""Per-rank structured event sink: JSONL with bounded file rotation.

One event per line, one file per rank (``events-rank00000.jsonl``), so a
multi-host pod writes without cross-process coordination and rank-0
aggregation is a glob.  Rotation is size-bounded (``max_bytes`` per file,
``keep`` rotated generations as ``.1`` .. ``.keep``) so a long training run
cannot fill the disk — the newest events are always in the unsuffixed file.

Event schema (every event, enforced by ``tests/test_telemetry.py``):

=========  ==============================================================
key        meaning
=========  ==============================================================
``ts``     monotonic seconds (``time.perf_counter()``) — per-process
           epoch; comparable within a rank, NOT across ranks
``kind``   ``meta`` | ``span`` | ``instant`` | ``counter`` | ``gauge`` |
           ``metrics``
``name``   dotted event name (``recorder.calc``, ``exchange.wire_bytes``)
``rank``   ``jax.process_index()`` of the emitting process
=========  ==============================================================

Kind-specific keys: spans add ``dur`` (seconds) and ``tid`` (thread id —
the Chrome-trace track); counters add ``value`` (increment) and ``total``
(cumulative); gauges add ``value``; ``metrics`` events carry the registry
snapshot at a flush boundary.  Arbitrary extra keys are tags.
"""

from __future__ import annotations

import json
import os
import threading


class EventSink:
    """Write JSON events to a rotating per-rank file.

    Thread-safe: the prefetcher's consumer and the train loop may emit
    concurrently.  Writes are line-buffered JSON; a crashed run leaves at
    worst one truncated final line, which the readers skip.

    A telemetry directory is ONE run's artifact: constructing a sink
    truncates this rank's live file (and drops its rotated generations),
    because aggregation reads every event in the directory and
    perf_counter epochs from different processes are incomparable —
    appending a rerun to a crashed run's file would produce a garbage
    merged timeline.  Use a fresh directory per run to keep history.
    """

    def __init__(self, directory: str, rank: int = 0,
                 max_bytes: int = 32 * 2**20, keep: int = 3):
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be positive, got {max_bytes}")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.rank = rank
        self.max_bytes = max_bytes
        self.keep = keep
        self.path = os.path.join(directory, f"events-rank{rank:05d}.jsonl")
        self._lock = threading.Lock()
        for stale in list(sink_files(directory, rank=rank)):
            if stale != self.path:
                os.remove(stale)
        # lint: atomic-publish-ok — live JSONL stream, not a publish:
        # line-buffered appends, and read_events skips a torn tail
        self._f = open(self.path, "w", buffering=1)
        self._size = 0

    def emit(self, event: dict) -> None:
        line = json.dumps(event, separators=(",", ":"), default=_jsonable)
        with self._lock:
            if self._f.closed:
                return  # late emitter (prefetch thread) after close(): drop
            self._f.write(line + "\n")
            self._size += len(line) + 1
            if self._size >= self.max_bytes:
                self._rotate()

    def _rotate(self) -> None:
        self._f.close()
        # shift generations: .keep-1 -> .keep, ..., current -> .1
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if self.keep >= 1:
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)
        # lint: atomic-publish-ok — fresh generation of the live JSONL
        # stream after rotation; same torn-tail-tolerant readers
        self._f = open(self.path, "w", buffering=1)
        self._size = 0

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def _jsonable(x):
    """Fallback encoder: numpy/jax scalars become plain floats/ints."""
    try:
        return x.item()
    except AttributeError:
        return repr(x)


def read_events(path: str) -> list[dict]:
    """Parse one JSONL file, skipping a torn final line from a crashed run."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail write
    return out


def tail_events(path: str, offset: int = 0) -> tuple[list[dict], int]:
    """Incrementally read complete events past ``offset`` bytes.

    The live-tailing primitive (ISSUE 13): a reader racing the writer
    must never consume a *partial* final line — the bytes after the last
    newline stay un-consumed and the returned offset points at them, so
    the next call re-reads the completed line.  A line that is complete
    but unparseable (a torn write the writer abandoned across a rotation
    boundary) is skipped, not raised.  A vanished file (rotated away
    between the caller's listing and the read) is an empty result, not an
    error.  -> (events, new_offset).
    """
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            chunk = f.read()
    except FileNotFoundError:
        return [], offset
    if not chunk:
        return [], offset
    end = chunk.rfind(b"\n")
    if end < 0:
        return [], offset  # only a partial line so far
    out = []
    for raw in chunk[:end].split(b"\n"):
        raw = raw.strip()
        if not raw:
            continue
        try:
            out.append(json.loads(raw))
        except json.JSONDecodeError:
            continue  # torn mid-file write (reader raced a rotation)
    return out, offset + end + 1


def sink_files(directory: str, rank: int | None = None) -> list[str]:
    """All event files under ``directory`` in chronological order
    (oldest rotation first, live file last), optionally for one rank."""
    import glob

    pat = (f"events-rank{rank:05d}.jsonl" if rank is not None
           else "events-rank*.jsonl")
    live = sorted(glob.glob(os.path.join(directory, pat)))
    out = []
    for p in live:
        gens = sorted(glob.glob(p + ".*"),
                      key=lambda q: int(q.rsplit(".", 1)[1]), reverse=True)
        out.extend(gens)
        out.append(p)
    return out
