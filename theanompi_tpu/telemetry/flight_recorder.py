"""Crash flight recorder: a bounded ring of recent events, dumped on
death (ISSUE 13).

A crashed or SIGTERMed run used to leave only an exit code and whatever
the sink had flushed.  The :class:`FlightRecorder` keeps the last
``capacity`` events in memory (fed by ``Telemetry.emit``, zero I/O per
event) plus the run's mesh/strategy fingerprint, and ``dump()`` writes an
atomic ``blackbox.json`` — last spans, health verdicts, the fingerprint,
the failure reason — at the moment of death:

- ``Rule.wait`` dumps on any exception escaping the training loop
  (including the cooperative-preemption ``PreemptionExit``);
- the resilience watchdog dumps right before its hang ``os._exit``;
- a SIGKILL leaves nothing, by definition — the supervisor's attempt
  record says so instead.

``resilience/supervisor.py`` harvests the file into the attempt records
of ``resilience.json`` and ``fleet/ledger.py`` persists it as the job's
failure cause.  Consumers read with plain ``json``
(:func:`read_blackbox` is a convenience, not a dependency).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

BLACKBOX_FILENAME = "blackbox.json"


def blackbox_path(directory: str, rank: int = 0) -> str:
    """Rank 0 owns the canonical name; other ranks get a suffixed file
    (single-process runs — the common case — always write
    ``blackbox.json``)."""
    if rank == 0:
        return os.path.join(directory, BLACKBOX_FILENAME)
    return os.path.join(directory, f"blackbox-rank{rank:05d}.json")


class FlightRecorder:
    """Bounded in-memory event ring + one-shot crash dump.

    Thread-safe: the train loop records while a watchdog/ticker thread
    may dump.  ``dump`` is idempotent-by-overwrite — the *last* dump
    wins, which is the right answer when a preemption dump is followed
    by a watchdog dump of the same wedged process.
    """

    def __init__(self, directory: str, capacity: int = 256, rank: int = 0):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.directory = directory
        self.capacity = capacity
        self.rank = rank
        self._ring: deque = deque(maxlen=capacity)
        self._fingerprint: dict = {}
        self._lock = threading.Lock()

    def record(self, event: dict) -> None:
        with self._lock:
            self._ring.append(event)

    def set_fingerprint(self, fingerprint: dict) -> None:
        """Attach the run-topology fingerprint (mesh axes, exchange
        strategy, model identity) the dump should carry."""
        with self._lock:
            self._fingerprint.update(fingerprint)

    def dump(self, reason: str, health: list | None = None,
             error: str | None = None) -> str:
        """Write ``blackbox.json`` atomically; -> its path.

        Best-effort callers (watchdog pre-exit) catch OSError themselves;
        this raises so test paths see real failures.
        """
        with self._lock:
            events = list(self._ring)
            fingerprint = dict(self._fingerprint)
        payload = {
            # wall stamp: the supervisor gates harvesting on file mtime
            # vs its own wall clock; the payload stamp is the human copy
            "wall_time": time.time(),  # lint: wall-ok — cross-process stamp
            "reason": reason,
            "pid": os.getpid(),
            "rank": self.rank,
            "fingerprint": fingerprint,
            "n_events": len(events),
            "events": events,
        }
        if error is not None:
            payload["error"] = error
        if health is not None:
            payload["health"] = health
        path = blackbox_path(self.directory, self.rank)
        os.makedirs(self.directory, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path


def read_blackbox(directory: str, rank: int = 0) -> dict | None:
    """Parse a dumped blackbox; None when absent/unreadable (a crashed
    dumper can at worst leave the previous complete file — the write is
    tmp + ``os.replace``)."""
    try:
        with open(blackbox_path(directory, rank)) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None
