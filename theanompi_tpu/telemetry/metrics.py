"""Metrics registry: counters, gauges, histograms + training-rate helpers.

The registry is host-side accumulation only — incrementing a counter or
observing a histogram sample is a dict update, never device work or I/O.
``snapshot()`` is called at flush boundaries (``print_freq`` in the
trainer) and its dict rides one ``metrics`` event through the sink.

MFU reuses the repo's existing FLOP accounting rather than re-deriving it:
``step_flops_estimate`` asks XLA's cost analysis through the trainer's
``compiled_step`` hook (the same source ``bench.py`` uses for conv nets)
and ``peak_flops`` defers to ``bench.chip_peak_flops()`` — one table, no
second copy of the v5e/v5p/v6 peaks.
"""

from __future__ import annotations

import re
from collections import defaultdict

import numpy as np

#: HLO collective op kinds, as spelled in compiled-module text
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

_COLL_DEF_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(-start|-done)?\(")


def hlo_collective_counts(hlo_text: str) -> dict[str, int]:
    """Count collective-op definitions per kind in compiled HLO text.

    The static cross-check for bucketed exchange (ISSUE 2): a fused-bucket
    step must compile to O(buckets) ``all-reduce`` ops, not O(leaves) — and
    ``zero1`` must show its ``reduce-scatter``/``all-gather`` pair.  Works
    on any backend, so CPU-mesh tests lint collective counts without TPU
    hardware (``tests/test_lint_collectives.py``); the exchange
    microbenchmark (``utils/scaling.py --exchange-bench``) reports the same
    numbers per strategy.  Async ``-start``/``-done`` pairs count once;
    operand references never carry parens, so only definitions match.
    """
    counts: dict[str, int] = {}
    for line in hlo_text.splitlines():
        if " = " not in line:
            continue
        for m in _COLL_DEF_RE.finditer(line):
            if m.group(2) == "-done":
                continue
            counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


# -- serving metric names (ISSUE 6) ------------------------------------------
# The serving scheduler emits through these registered names ONLY (it
# imports them from here — one source of truth, so dashboards and the
# Chrome-trace test can't drift from what the code emits).  Span semantics:
# ``serve.prefill`` wraps one sequence's full-prompt forward (tags:
# ``request``, ``prompt``, ``slot``); ``serve.decode`` wraps one fixed-batch
# decode step (tags: ``step``, ``batch``, ``requests`` — the per-request ids
# threaded through the trace).  Both close over materialized host results,
# so they measure execution, not dispatch; in the single-threaded serve loop
# they are disjoint by construction (locked by test).

SERVE_SPANS = ("serve.prefill", "serve.decode")
SERVE_INSTANTS = ("serve.admit", "serve.preempt", "serve.finish")
#: histograms: per-token decode latency and time-to-first-token, both ms
SERVE_HISTOGRAMS = ("serve.token_ms", "serve.ttft_ms")
SERVE_GAUGES = ("serve.tokens_per_sec", "serve.active", "serve.free_blocks")
SERVE_COUNTERS = ("serve.tokens", "serve.preemptions", "serve.requests")

# -- serving request-lifecycle names (ISSUE 14) ------------------------------
# The hardened request lifecycle emits one instant per NON-done terminal
# transition (``done`` keeps the original ``serve.finish``):
# ``serve.expire``: a request blew its ttft/total deadline (tags: request,
# which — "ttft" | "total", where — "queued" | "active" | "drain");
# ``serve.shed``: admission-time load shedding or a drain refused the
# request before any work was done (tags: request, reason, est_wait_ms);
# ``serve.fail``: the livelock guard refused a request that can never fit
# the KV pool (tags: request, need_blocks, pool_blocks); ``serve.drain``:
# the graceful-drain path toggled (tags: phase = "begin" | "end",
# in_flight).  Matching counters below; emitted through these registered
# names ONLY (same one-source-of-truth contract as above).
SERVE_LIFECYCLE_INSTANTS = ("serve.expire", "serve.shed", "serve.fail",
                            "serve.drain")
SERVE_LIFECYCLE_COUNTERS = ("serve.expired", "serve.shed_total",
                            "serve.failed")

# -- decode-kernel names (ISSUE 18) ------------------------------------------
# The fused paged-attention decode path (``ops/pallas_paged_attention.py``)
# is a per-run static choice, so it emits exactly once per serve run:
# ``serve.decode_kernel`` instants at startup record the RESOLVED impl
# (tags: impl — "kernel" | "kernel_interpret" | "fallback", requested —
# the --decode-kernel flag value); ``serve.decode_kernel.step_p50_ms``
# gauges the per-decode-step wall p50 at close, next to the existing
# SERVE_GAUGES — tagged with the impl so an A/B pair in one telemetry dir
# stays attributable.  Emitted through these registered names ONLY (same
# one-source-of-truth contract as above).
SERVE_DECODE_KERNEL_INSTANTS = ("serve.decode_kernel",)
SERVE_DECODE_KERNEL_GAUGES = ("serve.decode_kernel.step_p50_ms",)

# -- prefix-cache names (ISSUE 17) -------------------------------------------
# The radix prefix cache over the paged KV pool accounts every hit exactly:
# ``serve.prefix_hit`` counts admissions whose prompt matched a cached
# full-block prefix; ``serve.prefix_tokens_saved`` accumulates the matched
# prefix lengths — prefill K/V the engine did NOT recompute (the SERVE.json
# ``prefill_tokens_saved`` field is this counter's end-of-run value).
# ``serve.prefix_invalidate`` fires when the engine's ``params_version``
# moved (a live rollout swap/rollback) and the whole tree was dropped —
# cached K/V under old weights is silently wrong under new ones (tags:
# params_version, dropped).  Emitted through these registered names ONLY
# (same one-source-of-truth contract as above).
SERVE_PREFIX_COUNTERS = ("serve.prefix_hit", "serve.prefix_tokens_saved")
SERVE_PREFIX_INSTANTS = ("serve.prefix_invalidate",)

# -- live weight-rollout names (ISSUE 14) ------------------------------------
# ``serve.rollout``: the checkpoint-dir watcher hot-swapped a newly
# VERIFIED checkpoint between scheduler steps (tags: from_epoch, to_epoch,
# preempted — active slots recompute under the new weights, no request is
# dropped); ``serve.rollout_refused``: the newest candidate did not verify
# — corrupt or half-published — so the old weights keep serving (tags:
# epoch, reason); ``serve.rollback``: the health monitor's SLO/throughput
# verdict turned critical inside the probation window, so the previous
# weights were restored (tags: from_epoch, to_epoch, detector, reason).
SERVE_ROLLOUT_INSTANTS = ("serve.rollout", "serve.rollout_refused",
                          "serve.rollback")

# -- elastic-resume instant names (ISSUE 8) ----------------------------------
# The checkpoint reshard path emits through these registered names ONLY
# (same one-source-of-truth contract as the serving names above).
# ``reshard.plan``: a topology mismatch was replanned from the manifest
# alone (tags: epoch, old_n, new_n, strategy, lr_scale, n_buckets);
# ``reshard.apply``: the re-laid-out state was restored onto the live mesh
# (tags: epoch, old_n, new_n).  Both also land as events in the shared
# ``resilience.json`` audit log via ``resilience/events.py``.
RESHARD_INSTANTS = ("reshard.plan", "reshard.apply")

# -- data-plane counter names (ISSUE 10) -------------------------------------
# ``data.retries``: one count per retried shard/token-file read inside
# ``models.data.base.read_with_retry`` (tags: what — the caller's label for
# the resource).  A rising rate is the early witness of a flaky data mount
# long before DataReadError escalates; emitted through this registered name
# ONLY (same one-source-of-truth contract as the serving/reshard names).
DATA_COUNTERS = ("data.retries",)

# -- fleet instant names (ISSUE 11) ------------------------------------------
# Scheduler lifecycle instants, mirrored from the fleet events log into the
# fleet telemetry dir through these registered names ONLY (same
# one-source-of-truth contract as the serving/reshard/data names).
# ``fleet.schedule``: a queued job was gang-allocated devices and launched
# (tags: job, devices, priority); ``fleet.preempt``: a running job was
# SIGTERMed to free devices for a higher-priority one (tags: job, victim_of);
# ``fleet.resume``: a preempted job relaunched elastically on the devices
# that remain (tags: job, devices); ``fleet.complete``/``fleet.fail``: a
# job's final episode ended (tags: job, exit_code); ``fleet.hang``: a
# running job's HEALTH.json published a critical hang verdict (ISSUE 13 —
# tags: job, reason, step; the job's supervisor does the kill+restart, this
# event is the fleet-level audit line); ``fleet.drain``: a serving replica
# was asked to drain and exit clean — the router's scale-down path, ISSUE
# 19 (tags: job).
FLEET_INSTANTS = ("fleet.schedule", "fleet.preempt", "fleet.resume",
                  "fleet.complete", "fleet.fail", "fleet.hang",
                  "fleet.drain")

# -- router names (ISSUE 19) --------------------------------------------------
# The multi-replica router emits through these registered names ONLY (same
# one-source-of-truth contract as every family above).
# ``router.dispatch``: a request was appended to a replica's durable queue
# (tags: request, replica, sticky — whether conversation affinity chose the
# target); ``router.redistribute``: a dead replica's unanswered rids were
# re-appended to survivors' queues (tags: replica, n); ``router.replica_dead``:
# a replica's fleet job turned terminal with work outstanding (tags: replica,
# status); ``router.scale_up``/``router.scale_down``: the autoscale policy
# grew/drained the pool (tags: replica, pressure_s, replicas);
# ``router.duplicate``: a rid reached a second terminal record across
# replicas — the first one won, this is the exactly-once audit witness
# (tags: request, replica).
ROUTER_INSTANTS = ("router.dispatch", "router.redistribute",
                   "router.replica_dead", "router.scale_up",
                   "router.scale_down", "router.duplicate")
#: live pool state, gauged each router tick: replica count, aggregate
#: queued-but-unanswered tokens, rolling router-visible p99 TTFT
ROUTER_GAUGES = ("router.replicas", "router.backlog_tokens",
                 "router.ttft_p99_ms")
#: totals: requests admitted into the router, rids redistributed off dead
#: replicas
ROUTER_COUNTERS = ("router.requests", "router.redistributed")

# -- resilience instant names (ISSUE 13) -------------------------------------
# The resilience layer emits through these registered names ONLY (same
# one-source-of-truth contract as the serving/reshard/data/fleet names, now
# lint-enforced by the ``telemetry-registered-names`` rule).
# ``watchdog.stall``: the adaptive watchdog flagged a stalled step (tags:
# step, stalled_s, threshold_s, escalate); ``sentinel.skip``: the on-device
# non-finite guard skipped a poisoned batch (tags: step, total_skips);
# ``sentinel.nonfinite``: a host-side sentinel policy fired (tags: step,
# policy).
RESILIENCE_INSTANTS = ("watchdog.stall", "sentinel.skip",
                       "sentinel.nonfinite")

# -- live-health names (ISSUE 13) --------------------------------------------
# ``train.boundary`` instants bracket the trainer's beat-free epoch-boundary
# work (validate / checkpoint / prefetcher build) so the arrival-clock hang
# detector suspends across it instead of flagging a healthy boundary (tags:
# epoch, phase = "begin" | "end").  ``health.verdict`` mirrors each non-ok
# verdict the in-process HealthMonitor writes to HEALTH.json into the event
# stream (tags: detector, severity, reason).  Emitted through these
# registered names ONLY (same one-source-of-truth contract as above).
HEALTH_INSTANTS = ("train.boundary", "health.verdict")

# -- overlapped-exchange / quantization-ramp names (ISSUE 12) -----------------
# ``exchange.overlap``: span around (re)arming the chained step fn when
# ``exch_overlap`` is on (tags: strategy) — the overlap itself runs inside
# the compiled program, so arming is the only host-observable moment.
# Emitted through these registered names ONLY (same one-source-of-truth
# contract as the serving/reshard/data/fleet names above).
EXCHANGE_SPANS = ("exchange.overlap",)
#: ``exchange.ramp_phase``: the active ``exch_ramp`` phase index, gauged at
#: each phase switch (tags: epoch); pairs with the ``exchange.ramp_switch``
#: instant (tags: epoch, strategy, phase) and a re-emitted
#: ``exchange.accounting`` instant so wire-byte accounting tracks the phase.
EXCHANGE_GAUGES = ("exchange.ramp_phase",)
EXCHANGE_INSTANTS = ("exchange.ramp_switch",)
#: per-round ICI payload counter (tags: step, and ``shift`` for gossip
#: rounds) — the static accounting every exchange-bearing trainer emits
EXCHANGE_COUNTS = ("exchange.wire_bytes",)

# -- async-rule names (ISSUE 20) ----------------------------------------------
# The straggler-tolerant rules emit ONE instant per exchange/gossip round
# through these registered names ONLY (same one-source-of-truth contract as
# every family above), carrying the fields the ``async_staleness`` health
# detector consumes.  ``easgd.exchange`` (tags: step, staleness — steps
# since the previous elastic round, expected — tau, stretch — wall interval
# of this round vs the rolling median of previous rounds, drift — worst
# per-worker ``max_i(norm(p_i - center)/norm(center))`` computed ON DEVICE
# inside the compiled exchange, so it costs nothing between rounds);
# ``gosgd.round`` (tags: step, staleness — the max over workers of steps
# since each last participated in a push, expected — 1/p_push, shift,
# dropped — an injected ``gosgd:gossip_drop`` skipped the collective).
ASYNC_INSTANTS = ("easgd.exchange", "gosgd.round")
#: flush-boundary gauges mirroring the newest round's fields: per-worker
#: staleness (EASGD rounds are mutually synchronous, so one number; GOSGD
#: gauges the max and mean over workers) and EASGD's relative center drift
ASYNC_GAUGES = ("easgd.staleness", "easgd.center_drift",
                "gosgd.staleness_max", "gosgd.staleness_mean")

# -- step-attribution names (ISSUE 16) ----------------------------------------
# The StepAttributor (``telemetry/profile.py``) publishes per-segment
# per-step p50 milliseconds through these registered names ONLY at flush
# boundaries (same one-source-of-truth contract as above, lint-enforced).
# Train segments: data (prefetch dequeue + recorder wait), compute (fenced
# step), comm (exchange overlap), validate / checkpoint (boundary spans),
# host (unattributed remainder).  Serve segments: queue_wait / prefill /
# decode / rollout_swap.  ``attr.step_ms`` is the wall p50 the segment
# rows partition.
ATTR_GAUGES = ("attr.data_ms", "attr.compute_ms", "attr.comm_ms",
               "attr.validate_ms", "attr.checkpoint_ms", "attr.host_ms",
               "attr.queue_wait_ms", "attr.prefill_ms", "attr.decode_ms",
               "attr.rollout_swap_ms", "attr.step_ms")
#: segment name -> registered gauge name (derived, one source of truth)
ATTR_GAUGE_BY_SEGMENT = {
    name[len("attr."):-len("_ms")]: name for name in ATTR_GAUGES
}
#: per-device HBM watermarks sampled at fenced flush boundaries (worst
#: device wins the gauge; the per-device dict rides ATTRIB.json):
#: peak = high-water ``peak_bytes_in_use``, live = last ``bytes_in_use``,
#: limit = smallest ``bytes_limit``.  Absent entirely on CPU backends.
PROF_GAUGES = ("prof.hbm_peak_bytes", "prof.hbm_live_bytes",
               "prof.hbm_limit_bytes")
#: ``prof.window``: the jax.profiler trace window opened/closed at the
#: configured ``profile_window`` iterations (tags: phase = "start" |
#: "stop", iteration) — the host-trace marker that aligns the device
#: trace with the event stream.
PROF_INSTANTS = ("prof.window",)
#: ``ledger.regression``: the HealthMonitor's perf detector mirrored a
#: regression verdict from PERF_LEDGER.jsonl (tags: metric, delta_pct).
LEDGER_INSTANTS = ("ledger.regression",)


class MetricsRegistry:
    """Named counters (monotonic totals), gauges (last value), histograms
    (bounded sample windows with percentile readout)."""

    def __init__(self, histogram_window: int = 1024):
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self._hists: dict[str, list] = defaultdict(list)
        self._hist_window = histogram_window

    def count(self, name: str, value: float = 1.0) -> float:
        """Increment counter ``name``; -> new cumulative total."""
        self.counters[name] += value
        return self.counters[name]

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        h = self._hists[name]
        h.append(float(value))
        if len(h) > self._hist_window:
            del h[: len(h) - self._hist_window]

    def percentiles(self, name: str, qs=(50, 95, 99)) -> dict[str, float]:
        h = self._hists.get(name)
        if not h:
            return {}
        arr = np.asarray(h)
        return {f"p{q}": float(np.percentile(arr, q)) for q in qs}

    def snapshot(self) -> dict:
        """Flush-boundary view: totals, gauges, histogram percentiles."""
        out: dict = {}
        if self.counters:
            out["counters"] = dict(self.counters)
        if self.gauges:
            out["gauges"] = dict(self.gauges)
        hists = {k: self.percentiles(k) for k in self._hists}
        hists = {k: v for k, v in hists.items() if v}
        if hists:
            out["histograms"] = hists
        return out


def peak_flops() -> float | None:
    """Chip peak FLOP/s from bench.py's table (one source of truth)."""
    try:
        import bench

        return bench.chip_peak_flops()
    except Exception:  # lint: swallow-ok — optional probe, None = omit MFU
        return None


def step_flops_estimate(trainer, batch) -> float | None:
    """FLOPs per train step from XLA's cost analysis of the compiled step.

    Same accounting (and same caveats — Pallas custom-calls count zero,
    scan bodies count once) as ``bench.step_flops``; scaled by ``n_subb``
    for gradient accumulation exactly as bench does.  Returns None when
    cost analysis is unavailable; callers then simply omit MFU.
    """
    try:
        analysis = trainer.compiled_step(batch).cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        fl = float(analysis.get("flops", 0.0))
        if fl <= 0:
            return None
        n_subb = int(trainer.model.config.get("n_subb", 1) or 1)
        return fl * n_subb if n_subb > 1 else fl
    except Exception:  # lint: swallow-ok — cost analysis is best-effort
        return None


def mfu(flops_per_step: float, step_time_s: float,
        peak: float | None) -> float | None:
    if not peak or step_time_s <= 0:
        return None
    return flops_per_step / step_time_s / peak


#: the memory_stats keys worth keeping (the rest are allocator internals)
_MEMORY_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def per_device_memory_stats() -> dict[int, dict]:
    """HBM stats for EVERY local device: ``{device_index: {bytes_in_use,
    peak_bytes_in_use, bytes_limit}}``.

    None-safe throughout (ISSUE 16): devices whose ``memory_stats()`` is
    missing, raises, or returns empty (the CPU backend) are skipped, so
    CPU-only processes get ``{}`` rather than an exception — a straggling
    device without stats never hides the ones that have them.
    """
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # lint: swallow-ok — no backend at all
        return {}
    out: dict[int, dict] = {}
    for i, dev in enumerate(devices):
        try:
            stats = dev.memory_stats()
        except Exception:  # lint: swallow-ok — backends without memory stats
            continue
        if not stats:
            continue
        out[i] = {k: int(stats[k]) for k in _MEMORY_KEYS if k in stats}
    return out


def device_memory_stats() -> dict | None:
    """HBM stats of local device 0 (None on backends without them — CPU).

    Kept for existing callers; the per-device form above is the ISSUE 16
    watermark source.
    """
    stats = per_device_memory_stats()
    return stats.get(0) or None
