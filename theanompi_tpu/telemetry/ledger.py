"""Durable perf-regression ledger (ISSUE 16).

Every perf artifact this repo produces — the ``BENCH_*.json`` rounds,
``SCALING.json``, ``EXCHANGE*``/``SERVE.json`` reports, each run's
``ATTRIB.json`` — is a write-once snapshot: round 3's 2481 images/sec
says nothing about whether round 6 regressed.  :class:`PerfLedger` turns
them into one append-only trajectory:

- ``PERF_LEDGER.jsonl`` — one normalized record per measurement,
  appended (never rewritten) with a line-granular crash contract: a torn
  final line is skipped on read, everything before it survives.  Each
  record carries a content fingerprint so re-ingesting the same artifact
  (a re-run backfill, bench.py retrying) is idempotent.
- ``PERF_LEDGER.json`` — an atomically-replaced (tmp + ``os.replace``)
  per-metric summary snapshot for dashboards that want one file.
- ``check()`` — typed regression verdicts per metric: the latest point
  vs the trailing median of the previous ``window`` points, with the
  tolerance stated in the verdict.  Direction is inferred from the unit
  (``ms`` down is good, ``/sec``/``mfu``/``efficiency`` up is good).
  ``backend_unavailable`` stub runs are *recorded* (the trajectory shows
  the gap) but never enter a baseline and never regress.

Consumers: ``bench.py`` appends at every publish site, ``tmprof
--ledger`` drives update/check/backfill from the CLI, and the
HealthMonitor's ``perf`` detector surfaces regressions as live ``warn``
verdicts (ISSUE 13 plumbing, new detector).
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import threading
import time

LEDGER_FILENAME = "PERF_LEDGER.jsonl"
SNAPSHOT_FILENAME = "PERF_LEDGER.json"

#: default trailing-median window and relative tolerance for check()
DEFAULT_WINDOW = 5
DEFAULT_TOLERANCE = 0.10

#: artifact glob patterns backfill() ingests, in trajectory order —
#: sorted() within a pattern keeps BENCH_r01..r05 chronological
BACKFILL_PATTERNS = ("BENCH_r*.json", "BENCH_mfu_ladder.json",
                     "BENCH_transformer.json", "BENCH_unavailable.json",
                     "SCALING*.json", "EXCHANGE*.json", "SERVE*.json",
                     "ROUTER*.json",
                     "ROOFLINE*.json", "ATTRIB.json", "CONVERGE*.json")

#: unit substrings that mean lower-is-better; everything else (rates,
#: mfu, efficiency, shares) improves upward
_LOWER_BETTER_UNITS = ("ms", "seconds")


def _fingerprint(record: dict) -> str:
    """Content hash over the identity fields — the idempotency key."""
    ident = {k: record.get(k) for k in
             ("source", "kind", "metric", "run_id", "value")}
    return hashlib.sha1(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()[:16]


def make_record(source: str, kind: str, metric: str | None,
                value: float | None, unit: str = "",
                run_id: str | None = None, **extra) -> dict:
    rec = {
        "schema": 1,
        # wall stamp: trajectories correlate runs across machines/processes
        "ts": time.time(),  # lint: wall-ok — cross-process trajectory stamp
        "source": source,
        "kind": kind,
        "metric": metric,
        "value": None if value is None else float(value),
        "unit": unit,
        "run_id": run_id,
    }
    if extra:
        rec["extra"] = extra
    rec["fp"] = _fingerprint(rec)
    return rec


def lower_is_better(metric: str, unit: str) -> bool:
    m = (metric or "").lower()
    u = (unit or "").lower()
    if m.endswith("_ms") or "step_ms" in m or "ttft" in m or "latency" in m:
        return True
    return any(x == u or u.endswith(x) for x in _LOWER_BETTER_UNITS)


# -- artifact classifiers ----------------------------------------------------

def _bench_line_records(source: str, line: dict,
                        prefix: str = "") -> list[dict]:
    """Records out of one bench primary-output dict (the ``{"metric":
    ..., "value": ...}`` line bench.py prints and re-publishes)."""
    metric = line.get("metric")
    if metric is None:
        return []
    recs = [make_record(source, "bench", prefix + metric,
                        line.get("value"), line.get("unit", ""),
                        run_id=line.get("run_id"),
                        vs_baseline=line.get("vs_baseline"))]
    if line.get("step_ms") is not None:
        recs.append(make_record(source, "bench",
                                f"{prefix}{metric}.step_ms",
                                line["step_ms"], "ms",
                                run_id=line.get("run_id")))
    if line.get("mfu") is not None:
        recs.append(make_record(source, "bench", f"{prefix}{metric}.mfu",
                                line["mfu"], "mfu",
                                run_id=line.get("run_id")))
    return recs


def classify_artifact(name: str, payload: dict) -> list[dict]:
    """Normalize one known artifact into ledger records.

    Unknown shapes yield nothing rather than noise — the ledger only
    tracks metrics something can be held to.
    """
    if not isinstance(payload, dict):
        return []
    base = os.path.basename(name)
    run_id = payload.get("run_id")
    # deterministic backend-absence stubs: recorded, never baselined
    if payload.get("status") == "backend_unavailable":
        return [make_record(base, "backend_unavailable", None, None,
                            run_id=run_id, error=payload.get("error"))]
    # BENCH_rNN.json: a driver wrapper {n, cmd, rc, tail, parsed}
    if "parsed" in payload and "rc" in payload:
        parsed = payload.get("parsed")
        if not parsed or payload.get("rc"):
            return [make_record(base, "backend_unavailable", None, None,
                                run_id=run_id, rc=payload.get("rc"))]
        return _bench_line_records(base, parsed)
    # SERVE.json: bench.py serve-mode report.  MUST precede the bare
    # bench-line branch — serve_report() also carries top-level
    # ``metric``/``value``, and the generic branch would swallow it,
    # dropping the latency percentiles and prefix-cache accounting.
    if base.startswith("SERVE"):
        recs = []
        tps = payload.get("value", payload.get("tokens_per_sec"))
        if tps is not None:
            recs.append(make_record(base, "serve", "serve.tokens_per_sec",
                                    tps, "tokens/sec", run_id=run_id))
        for key in ("ttft", "token"):
            pcts = payload.get(f"{key}_ms")
            pcts = pcts if isinstance(pcts, dict) else {}
            for p in ("p50", "p99"):
                val = pcts.get(p, payload.get(f"{key}_{p}_ms"))
                if val is not None:
                    recs.append(make_record(base, "serve",
                                            f"serve.{key}_{p}_ms", val,
                                            "ms", run_id=run_id))
        # ISSUE 18 decode-kernel A/B: per-step wall is keyed BY VARIANT so
        # a kernel-on run never regresses against a fallback baseline
        variant = payload.get("decode_kernel")
        step_pcts = payload.get("decode_step_ms")
        if variant and isinstance(step_pcts, dict):
            for p in ("p50", "p99"):
                if step_pcts.get(p) is not None:
                    recs.append(make_record(
                        base, "serve",
                        f"serve.decode.{variant}.step_{p}_ms",
                        step_pcts[p], "ms", run_id=run_id))
        # prefix-cache accounting (ISSUE 17): only a cache-on run enters
        # the trajectory — cache-off zeros would poison the baseline
        if payload.get("prefix_cache"):
            for field, unit in (("prefix_hit_rate", "rate"),
                                ("prefill_tokens_saved", "tokens")):
                if payload.get(field) is not None:
                    recs.append(make_record(base, "serve",
                                            f"serve.{field}",
                                            payload[field], unit,
                                            run_id=run_id))
        return recs
    # ROUTER.json: the tmrouter multi-replica report (ISSUE 19).  Same
    # trap as SERVE — it carries top-level ``metric``/``value``, so it
    # MUST precede the bare bench-line branch or the TTFT percentiles
    # and replica-count trajectory would be dropped.
    if base.startswith("ROUTER"):
        recs = []
        tps = payload.get("value", payload.get("tokens_per_sec"))
        if tps is not None:
            recs.append(make_record(base, "router",
                                    "router.tokens_per_sec", tps,
                                    "tokens/sec", run_id=run_id))
        pcts = payload.get("ttft_ms")
        pcts = pcts if isinstance(pcts, dict) else {}
        for p in ("p50", "p99"):
            if pcts.get(p) is not None:
                recs.append(make_record(base, "router",
                                        f"router.ttft_{p}_ms", pcts[p],
                                        "ms", run_id=run_id))
        if payload.get("replicas_peak") is not None:
            recs.append(make_record(base, "router", "router.replicas_peak",
                                    payload["replicas_peak"], "replicas",
                                    run_id=run_id))
        return recs
    # CONVERGE.json: utils/converge.py gate report (ISSUE 20 trending).
    # Each row's margin (target_error - best_val_error) enters the
    # trajectory as a higher-is-better point, so a rule that still
    # passes but with shrinking headroom shows up in check() before it
    # ever fails the gate.  Async rules (EASGD/GOSGD) ride the same
    # branch — the rule name is carried in extra for filtering.
    if base.startswith("CONVERGE") and isinstance(
            payload.get("results"), list):
        recs = []
        for row in payload["results"]:
            if not isinstance(row, dict):
                continue
            target = row.get("target_error")
            best = row.get("best_val_error")
            if target is None or best is None:
                continue
            name_key = row.get("model", "model")
            recs.append(make_record(
                base, "converge", f"converge.{name_key}.margin",
                float(target) - float(best), "margin", run_id=run_id,
                rule=row.get("rule"), passed=row.get("passed"),
                epochs_to_target=row.get("epochs_to_target")))
        return recs
    # BENCH_transformer.json / a bare bench line
    if "metric" in payload and "value" in payload:
        return _bench_line_records(base, payload)
    # BENCH_mfu_ladder.json: {what, rows: [{dim, n_layers, batch, ...}]}
    if base.startswith("BENCH_") and isinstance(payload.get("rows"), list):
        recs = []
        for row in payload["rows"]:
            if not isinstance(row, dict):
                continue
            key = f"mfu_ladder.d{row.get('dim')}xL{row.get('n_layers')}"
            if row.get("tokens_per_sec") is not None:
                recs.append(make_record(base, "bench",
                                        f"{key}.tokens_per_sec",
                                        row["tokens_per_sec"], "tokens/sec",
                                        run_id=run_id))
            if row.get("mfu") is not None:
                recs.append(make_record(base, "bench", f"{key}.mfu",
                                        row["mfu"], "mfu", run_id=run_id))
        return recs
    # SCALING.json: {model, strategy, per_n: {n: {...}}}
    if "per_n" in payload:
        recs = []
        model = payload.get("model", "model")
        strat = payload.get("strategy", "")
        for n, row in sorted(payload["per_n"].items(),
                             key=lambda kv: int(kv[0])):
            if not isinstance(row, dict):
                continue
            key = f"scaling.{model}.{strat}.n{n}"
            for field, unit in (("imgs_per_sec", "images/sec"),
                                ("efficiency", "efficiency"),
                                ("step_ms", "ms")):
                if row.get(field) is not None:
                    recs.append(make_record(base, "scaling",
                                            f"{key}.{field}", row[field],
                                            unit, run_id=run_id))
        return recs
    # EXCHANGE*.json: {strategy -> {ms_per_exchange, ...}} or rows
    if base.startswith("EXCHANGE"):
        recs = []
        rows = payload.get("rows")
        items = (enumerate(rows) if isinstance(rows, list)
                 else payload.items())
        for key, row in items:
            if not isinstance(row, dict):
                continue
            label = row.get("strategy", key)
            for field in ("ms_per_exchange", "ms", "gbps"):
                if row.get(field) is not None:
                    unit = "ms" if "ms" in field else "gbps"
                    recs.append(make_record(base, "exchange",
                                            f"exchange.{label}.{field}",
                                            row[field], unit,
                                            run_id=run_id))
        return recs
    # ROOFLINE*.json: utils/roofline.py per-op roofline report.  Only the
    # whole-step aggregates enter the trajectory — per-op rows churn with
    # every fusion-boundary change and would drown check() in renames.
    if isinstance(payload.get("ops"), list) and "device_step_ms" in payload:
        label = payload.get("model")
        if not label:
            stem = base[:-5] if base.endswith(".json") else base
            label = (stem[len("ROOFLINE_"):]
                     if stem.startswith("ROOFLINE_") else "default")
        recs = []
        if payload.get("device_step_ms") is not None:
            recs.append(make_record(base, "roofline",
                                    f"roofline.{label}.device_step_ms",
                                    payload["device_step_ms"], "ms",
                                    run_id=run_id))
        # roof-proximity shares: the fraction of step time spent at
        # >= half / >= 80% of the relevant roof — up is good
        for field in ("time_share_at_half_roof", "time_share_at_80pct_roof"):
            if payload.get(field) is not None:
                recs.append(make_record(base, "roofline",
                                        f"roofline.{label}.{field}",
                                        payload[field], "share",
                                        run_id=run_id))
        return recs
    # ATTRIB.json: per-run attribution summary (telemetry/profile.py)
    if "per_rank" in payload:
        recs = []
        rid = run_id or (f"pid{payload['pid']}" if "pid" in payload
                         else None)
        for rank, res in sorted(payload["per_rank"].items()):
            mode = res.get("mode", "train")
            wall = (res.get("wall_step") or {}).get("p50_ms")
            if wall is not None:
                recs.append(make_record(base, "attrib",
                                        f"attrib.{mode}.step_ms", wall,
                                        "ms", run_id=rid, rank=rank))
            for seg, st in sorted((res.get("segments") or {}).items()):
                if st.get("share") is not None:
                    recs.append(make_record(
                        base, "attrib", f"attrib.{mode}.{seg}_share",
                        st["share"], "share", run_id=rid, rank=rank))
        return recs
    return []


# -- reading -----------------------------------------------------------------

def read_ledger(path: str) -> list[dict]:
    """All well-formed records, append order.  A torn final line (the
    crash contract of an append-only log) is skipped, as are foreign
    lines — readers never fail on a half-written ledger."""
    records: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("schema") == 1:
                    records.append(rec)
    except OSError:
        return []
    return records


def trajectories(records: list[dict]) -> dict[str, list[dict]]:
    """metric -> append-ordered measurable points.  Stub runs
    (``backend_unavailable``) carry no metric and drop out here — they
    stay in the log as the gap's witness but never enter a baseline."""
    out: dict[str, list[dict]] = {}
    for rec in records:
        if rec.get("kind") == "backend_unavailable":
            continue
        metric, value = rec.get("metric"), rec.get("value")
        if metric is None or value is None:
            continue
        out.setdefault(metric, []).append(rec)
    return out


def _median(xs) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def check_records(records: list[dict],
                  tolerance: float = DEFAULT_TOLERANCE,
                  window: int = DEFAULT_WINDOW) -> list[dict]:
    """Typed per-metric verdicts: latest vs trailing median.

    ``regression`` — latest is worse than the median of the previous
    ``window`` points by more than ``tolerance`` (relative);
    ``improvement`` — better by more than ``tolerance``; ``ok`` —
    within band; ``insufficient_history`` — fewer than 2 points.
    """
    verdicts = []
    for metric, points in sorted(trajectories(records).items()):
        latest = points[-1]
        unit = latest.get("unit", "")
        down = lower_is_better(metric, unit)
        base = {"metric": metric, "unit": unit,
                "direction": "lower_is_better" if down
                else "higher_is_better",
                "latest": latest["value"], "n_points": len(points),
                "tolerance_pct": round(tolerance * 100, 2)}
        if len(points) < 2:
            verdicts.append({**base, "verdict": "insufficient_history",
                             "baseline": None, "delta_pct": None})
            continue
        baseline = _median([p["value"] for p in points[:-1]][-window:])
        delta = ((latest["value"] - baseline) / baseline if baseline
                 else 0.0)
        worse = delta > tolerance if down else delta < -tolerance
        better = delta < -tolerance if down else delta > tolerance
        verdict = ("regression" if worse
                   else "improvement" if better else "ok")
        verdicts.append({**base, "verdict": verdict,
                         "baseline": round(baseline, 6),
                         "delta_pct": round(delta * 100, 2)})
    return verdicts


def check_ledger(path: str, tolerance: float = DEFAULT_TOLERANCE,
                 window: int = DEFAULT_WINDOW) -> list[dict]:
    """Read + check in one lock-free call — the HealthMonitor's perf
    detector uses this so no ledger lock nests inside the health lock."""
    return check_records(read_ledger(path), tolerance, window)


def regressions(verdicts: list[dict]) -> list[dict]:
    return [v for v in verdicts if v["verdict"] == "regression"]


# -- the writer --------------------------------------------------------------

class PerfLedger:
    """Append-only writer + snapshot publisher for one ledger file.

    Thread-safe: bench.py's publish sites and a run's close path may
    append concurrently.  Appends are line-granular (single ``write`` of
    complete lines, flushed) so a crash tears at most the final line,
    which :func:`read_ledger` skips.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def records(self) -> list[dict]:
        with self._lock:
            return read_ledger(self.path)

    def append(self, records: list[dict], dedup: bool = True) -> list[dict]:
        """Append normalized records; -> those actually written.

        ``dedup`` skips records whose fingerprint is already in the log,
        making artifact ingestion idempotent across re-runs.
        """
        if not records:
            return []
        with self._lock:
            if dedup:
                seen = {r.get("fp") for r in read_ledger(self.path)}
                records = [r for r in records if r.get("fp") not in seen]
            if not records:
                return []
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            payload = "".join(json.dumps(r) + "\n" for r in records)
            # heal a crash-torn tail: without the newline the first new
            # record would concatenate onto the torn line and both lines
            # would be unreadable forever
            try:
                with open(self.path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        payload = "\n" + payload
            except OSError:  # lint: swallow-ok — no file yet / empty: nothing to heal
                pass
            # append-only journal: the log IS the artifact, rewriting it
            # via tmp+replace would lose concurrent writers' lines — the
            # torn-tail-skipping reader is the crash contract instead
            with open(self.path, "a") as f:  # lint: atomic-publish-ok — append-only JSONL journal; readers skip a torn tail
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
        return records

    def ingest_artifact(self, path: str) -> list[dict]:
        """Classify + append one artifact file; -> records written."""
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return []
        return self.append(classify_artifact(path, payload))

    def ingest(self, source: str, payload: dict) -> list[dict]:
        """Classify + append an in-memory artifact (bench.py's publish
        sites hand over the dict they just wrote)."""
        return self.append(classify_artifact(source, payload))

    def check(self, tolerance: float = DEFAULT_TOLERANCE,
              window: int = DEFAULT_WINDOW) -> list[dict]:
        return check_records(self.records(), tolerance, window)

    def snapshot(self, path: str | None = None,
                 tolerance: float = DEFAULT_TOLERANCE) -> str:
        """Atomically publish the per-metric summary JSON (tmp +
        ``os.replace`` — a reader never sees a torn file)."""
        records = self.records()
        verdicts = check_records(records, tolerance)
        path = path or os.path.join(
            os.path.dirname(self.path) or ".", SNAPSHOT_FILENAME)
        payload = {
            "updated": time.time(),  # lint: wall-ok — cross-process stamp
            "ledger": os.path.basename(self.path),
            "n_records": len(records),
            "n_stub_runs": sum(1 for r in records
                               if r.get("kind") == "backend_unavailable"),
            "verdicts": verdicts,
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path

    def backfill(self, root: str) -> list[dict]:
        """One-shot ingest of every known artifact under ``root`` (the
        repo dir), in trajectory order.  Idempotent via fingerprints."""
        written: list[dict] = []
        for pattern in BACKFILL_PATTERNS:
            for path in sorted(glob.glob(os.path.join(root, pattern))):
                written.extend(self.ingest_artifact(path))
        return written


def bench_ledger_append(payload: dict, source: str,
                        repo_dir: str | None = None) -> None:
    """bench.py's one-liner: append one published artifact to the repo
    ledger (``BENCH_LEDGER`` overrides the path; ``BENCH_LEDGER=0``
    disables).  Never raises — a ledger hiccup must not cost the bench
    its primary output line."""
    dest = os.environ.get("BENCH_LEDGER")
    if dest == "0":
        return
    if not dest:
        dest = os.path.join(repo_dir or os.getcwd(), LEDGER_FILENAME)
    try:
        PerfLedger(dest).ingest(source, payload)
    except Exception:  # lint: swallow-ok — advisory trajectory, bench line wins
        pass
