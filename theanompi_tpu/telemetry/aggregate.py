"""Rank-0 aggregation: merge per-rank sinks, summarize cross-rank skew.

Run at the end of training (``Rule.wait`` calls :func:`finalize` on
process 0).  Reads every ``events-rank*.jsonl`` visible under the
telemetry directory — on a multi-host pod each host only sees its own
ranks' files unless the directory is shared storage; the summary says how
many ranks it covered, so a partial view is explicit, never silent.

Cross-rank comparisons use span *durations* only (per-rank perf_counter
epochs are not comparable):

- ``step_skew``: per train step tagged on ``train.step`` spans, the
  max-min step duration across ranks — sustained skew means a straggler,
  since BSP's fused collective forces laggards onto the critical path;
- ``straggler``: the rank with the highest mean step duration and its
  ratio to the fleet mean.
"""

from __future__ import annotations

import json
import os
from collections import defaultdict

import numpy as np

from theanompi_tpu.telemetry.chrome_trace import write_chrome_trace
from theanompi_tpu.telemetry.sink import read_events, sink_files


def load_all_events(directory: str) -> list[dict]:
    events: list[dict] = []
    for p in sink_files(directory):
        events.extend(read_events(p))
    return events


def summarize(directory: str) -> dict:
    """-> the cross-rank summary dict (also what ``summary.json`` holds)."""
    return summarize_events(load_all_events(directory))


def summarize_events(events: list[dict]) -> dict:
    ranks = sorted({ev.get("rank", 0) for ev in events})

    # per-rank step spans: rank -> {step -> dur}
    step_durs: dict[int, dict[int, float]] = defaultdict(dict)
    seg_totals: dict[int, dict[str, float]] = defaultdict(
        lambda: defaultdict(float))
    counters: dict[int, dict[str, float]] = {}
    for ev in events:
        r = ev.get("rank", 0)
        if ev.get("kind") == "span":
            if ev.get("name") == "train.step" and "step" in ev:
                step_durs[r][int(ev["step"])] = ev["dur"]
            elif str(ev.get("name", "")).startswith("recorder."):
                seg_totals[r][ev["name"].split(".", 1)[1]] += ev["dur"]
        elif ev.get("kind") == "metrics" and "counters" in ev:
            counters[r] = ev["counters"]  # last flush wins (cumulative)

    per_rank = {}
    for r in ranks:
        durs = np.asarray(sorted(step_durs[r].values())) if step_durs[r] else None
        row: dict = {"steps": len(step_durs[r])}
        if durs is not None and durs.size:
            row["step_ms"] = {
                "mean": round(float(durs.mean()) * 1e3, 3),
                "p50": round(float(np.percentile(durs, 50)) * 1e3, 3),
                "p95": round(float(np.percentile(durs, 95)) * 1e3, 3),
            }
        if seg_totals.get(r):
            row["segment_totals_s"] = {
                k: round(v, 4) for k, v in sorted(seg_totals[r].items())
            }
        if r in counters:
            row["counters"] = counters[r]
        per_rank[str(r)] = row

    out: dict = {"n_ranks": len(ranks), "per_rank": per_rank}

    # skew: steps observed on every rank
    if len(ranks) > 1:
        common = set.intersection(
            *(set(step_durs[r]) for r in ranks)) if all(
            step_durs[r] for r in ranks) else set()
        skews = [max(step_durs[r][s] for r in ranks)
                 - min(step_durs[r][s] for r in ranks)
                 for s in sorted(common)]
        if skews:
            arr = np.asarray(skews)
            out["step_skew_ms"] = {
                "mean": round(float(arr.mean()) * 1e3, 3),
                "max": round(float(arr.max()) * 1e3, 3),
                "steps_compared": int(arr.size),
            }
        means = {r: float(np.mean(list(step_durs[r].values())))
                 for r in ranks if step_durs[r]}
        if means:
            worst = max(means, key=means.get)
            fleet = float(np.mean(list(means.values())))
            out["straggler"] = {
                "rank": worst,
                "mean_step_ms": round(means[worst] * 1e3, 3),
                "vs_fleet_mean": round(means[worst] / fleet, 3) if fleet else None,
            }
    return out


def finalize(directory: str) -> dict:
    """Write ``trace.json`` (all ranks) + ``summary.json``; -> summary.

    Events are loaded and parsed ONCE and fed to both outputs — rank
    files can run to ``max_bytes * keep`` each, so double-parsing them
    at end-of-run would be real time on rank 0.
    """
    events = load_all_events(directory)
    write_chrome_trace(events, os.path.join(directory, "trace.json"))
    summary = summarize_events(events)
    spath = os.path.join(directory, "summary.json")
    with open(spath + ".tmp", "w") as f:
        json.dump(summary, f, indent=1)
    os.replace(spath + ".tmp", spath)
    return summary


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="Aggregate a telemetry dir: Chrome trace + skew summary")
    p.add_argument("directory")
    args = p.parse_args(argv)
    summary = finalize(args.directory)
    print(json.dumps(summary, indent=1))


if __name__ == "__main__":
    main()
