"""``tmhealth`` — print/refresh live health verdicts (ISSUE 13).

Tails one telemetry directory, or every per-job telemetry directory of a
fleet dir (``--fleet``), and prints each run's verdicts:

    tmhealth ./telemetry                 # one run, one shot
    tmhealth ./pool --fleet --follow     # whole fleet, refreshing
    python -m theanompi_tpu.telemetry ./telemetry   # same entry point

The live path reads the atomically-published ``HEALTH.json`` the run's
in-process monitor maintains.  When no ``HEALTH.json`` exists (the run
predates ISSUE 13, or health was disabled), the detectors are replayed
offline over the event files — arrival-clock hang detection is then
judged from sink-file staleness instead, since recorded ``ts`` values
are per-process epochs.

Exit contract (plain codes — this is a read-only reporting tool, not a
party to the supervisor's 70/75–79 contract): ``0`` no critical
verdicts, ``1`` at least one critical, ``2`` usage error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

from theanompi_tpu.telemetry.health import (
    SEV_CRITICAL,
    SEV_OK,
    read_health,
    replay_events,
)
from theanompi_tpu.telemetry.sink import read_events, sink_files

#: replayed runs with no session_end whose newest event file is older
#: than this are reported hung (the offline stand-in for the live
#: arrival-clock deadline)
STALE_HANG_S = 60.0


def scan_dir(directory: str, stale_hang_s: float = STALE_HANG_S) -> dict:
    """-> {"dir", "source", "updated_s" | None, "verdicts": [...]}"""
    health = read_health(directory)
    now = time.time()  # lint: wall-ok — compared against file mtimes
    if health is not None:
        return {
            "dir": directory,
            "source": "HEALTH.json",
            "updated_s": round(now - float(health.get("updated", now)), 1),
            "steps": health.get("steps"),
            "verdicts": list(health.get("verdicts", [])),
        }
    files = sink_files(directory)
    events: list[dict] = []
    for p in files:
        events.extend(read_events(p))
    mon = replay_events(events, directory=directory)
    verdicts = mon.verdicts()
    ended = any(ev.get("kind") == "meta" and ev.get("name") == "session_end"
                for ev in events)
    if files and not ended:
        age = now - max(os.path.getmtime(p) for p in files
                        if os.path.exists(p))
        if age > stale_hang_s:
            verdicts.append({
                "detector": "hang", "severity": SEV_CRITICAL,
                "reason": (f"no session_end and event files idle for "
                           f"{age:.0f}s"),
                "fields": {"stalled_s": round(age, 1),
                           "deadline_s": stale_hang_s}})
    return {"dir": directory, "source": "replay", "updated_s": None,
            "steps": None, "verdicts": verdicts}


def fleet_telemetry_dirs(fleet_dir: str) -> list[str]:
    """Per-job telemetry dirs of a fleet dir (the ``jobs/<id>/telemetry``
    layout the FleetScheduler creates)."""
    return sorted(glob.glob(os.path.join(fleet_dir, "jobs", "*",
                                         "telemetry")))


def _format(report: dict) -> str:
    lines = []
    where = report["dir"]
    src = report["source"]
    upd = report.get("updated_s")
    head = f"{where}  [{src}" + (
        f", updated {upd:.0f}s ago]" if upd is not None else "]")
    lines.append(head)
    verdicts = report["verdicts"]
    if not verdicts:
        lines.append("  (no verdicts — no health data and no events)")
    for v in verdicts:
        sev = v.get("severity", SEV_OK)
        mark = {"ok": " ", "warn": "!", "critical": "X"}.get(sev, "?")
        step = v.get("step")
        at = f" @step {step}" if step is not None else ""
        lines.append(f"  [{mark}] {v.get('detector'):<11} {sev:<8} "
                     f"{v.get('reason', '')}{at}")
    return "\n".join(lines)


def _scan_all(dirs: list[str], stale_hang_s: float) -> list[dict]:
    return [scan_dir(d, stale_hang_s) for d in dirs]


def _any_critical(reports: list[dict]) -> bool:
    return any(v.get("severity") == SEV_CRITICAL
               for rep in reports for v in rep["verdicts"])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tmhealth",
        description="Print/refresh live run-health verdicts from a "
                    "telemetry dir (or a fleet's per-job dirs)")
    p.add_argument("directory",
                   help="telemetry dir, or a fleet dir with --fleet")
    p.add_argument("--fleet", action="store_true",
                   help="treat DIRECTORY as a fleet dir: scan every "
                        "jobs/<id>/telemetry under it")
    p.add_argument("--follow", action="store_true",
                   help="refresh until interrupted")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh interval seconds (with --follow)")
    p.add_argument("--stale-hang-s", type=float, default=STALE_HANG_S,
                   help="offline replay: report hang when event files "
                        "are idle this long without a session_end")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (one JSON doc per scan)")
    args = p.parse_args(argv)

    if not os.path.isdir(args.directory):
        print(f"tmhealth: error: no such directory: {args.directory}",
              file=sys.stderr)
        return 2
    while True:
        if args.fleet:
            dirs = fleet_telemetry_dirs(args.directory)
            if not dirs:
                print(f"tmhealth: error: no jobs/*/telemetry under "
                      f"{args.directory}", file=sys.stderr)
                return 2
        else:
            dirs = [args.directory]
        reports = _scan_all(dirs, args.stale_hang_s)
        if args.as_json:
            print(json.dumps({"reports": reports}, indent=1), flush=True)
        else:
            print("\n".join(_format(r) for r in reports), flush=True)
        if not args.follow:
            return 1 if _any_critical(reports) else 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 1 if _any_critical(reports) else 0


if __name__ == "__main__":
    sys.exit(main())
