"""Step-time attribution profiler (ISSUE 16).

ROADMAP item 3 says compute is now the ceiling (MFU 0.52 LM / 0.299
resnet50) — but until now nothing in the repo could say *where* a step's
milliseconds go.  :class:`StepAttributor` decomposes every step from the
span/instant stream the trainer and serving scheduler already emit — no
new instrumentation on the hot path, the existing events are re-read as
a time budget:

- **train mode** (any ``train.step`` span seen): ``data`` =
  ``recorder.wait`` + ``prefetch.dequeue`` (the dequeue nests inside the
  wait, so the union — not the sum — is charged), ``compute`` = the
  fenced ``train.step`` / ``recorder.calc`` spans, ``comm`` =
  ``exchange.overlap`` + ``recorder.comm``, ``validate`` /
  ``checkpoint`` = the boundary spans between ``train.boundary``
  instants, ``host`` = whatever remains of the wall window — the
  unattributed dispatch/python gap.
- **serve mode** (no train steps, ``serve.*`` spans seen): ``prefill`` /
  ``decode`` from the scheduler's spans; unclaimed gaps containing a
  ``serve.rollout*``/``serve.rollback`` instant become ``rollout_swap``,
  every other gap is ``queue_wait``.

Overlapping spans never double-charge: segments claim the timeline in a
fixed precedence order (:data:`CLAIM_ORDER`) and each claim subtracts
what earlier segments took, so the per-segment totals partition the wall
window exactly — ``sum(segments) == window`` by construction, which is
what lets the acceptance test demand the table sum to the measured wall
time.

Attribution is per rank and per thread: only spans on the step-emitting
thread are charged (the async checkpoint writer's ``checkpoint.write``
overlaps training and must not be billed as boundary stall; the blocking
``checkpoint.snapshot`` is on the main thread and is).

Publication: registered ``attr.*`` gauges at flush boundaries, an
atomically-replaced ``ATTRIB.json`` (p50/p99 per segment, dominant-term
verdict), and per-device HBM watermarks sampled at the same fenced
boundaries (``prof.hbm_*`` — None-safe on CPU).  Off means off: a
``Telemetry`` constructed without ``profile=`` makes zero calls here.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict

import numpy as np

from theanompi_tpu.telemetry.metrics import (
    ATTR_GAUGE_BY_SEGMENT,
    PROF_GAUGES,
    per_device_memory_stats,
)

ATTRIB_FILENAME = "ATTRIB.json"

#: train-mode segments, in claim-precedence order (earlier claims win the
#: overlap); ``host`` is the remainder and never claims
TRAIN_SEGMENTS = ("comm", "checkpoint", "validate", "data", "compute",
                  "host")
#: serve-mode segments; ``queue_wait``/``rollout_swap`` split the remainder
SERVE_SEGMENTS = ("prefill", "decode", "rollout_swap", "queue_wait")

#: span name -> train segment (``checkpoint.*`` matches by prefix)
_TRAIN_SPAN_SEGMENT = {
    "recorder.wait": "data",
    "prefetch.dequeue": "data",
    "recorder.calc": "compute",
    "train.step": "compute",
    "recorder.comm": "comm",
    "exchange.overlap": "comm",
    "validate": "validate",
}
_SERVE_SPAN_SEGMENT = {"serve.prefill": "prefill", "serve.decode": "decode"}
_ROLLOUT_INSTANTS = ("serve.rollout", "serve.rollout_refused",
                     "serve.rollback")

#: fold threshold: the streaming attributor buffers raw events and folds
#: them into cumulative totals once the buffer crosses this, so a long
#: run's memory stays bounded (~1.6k steps of train events per fold)
_FOLD_EVENTS = 8192
#: bounded per-segment per-step sample windows (percentile source)
_SAMPLE_WINDOW = 2048


# -- interval arithmetic -----------------------------------------------------
# All segment math is on half-open [start, end) intervals in perf_counter
# seconds.  merge/subtract keep lists sorted and disjoint, so measure()
# is a plain sum and nothing double-counts.

def _merge(intervals: list[tuple]) -> list[tuple]:
    """Sorted union of possibly-overlapping intervals."""
    out: list[tuple] = []
    for a, b in sorted(intervals):
        if b <= a:
            continue
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _subtract(intervals: list[tuple], claimed: list[tuple]) -> list[tuple]:
    """``intervals`` minus ``claimed`` (both sorted & disjoint)."""
    out: list[tuple] = []
    for a, b in intervals:
        cur = a
        for ca, cb in claimed:
            if cb <= cur:
                continue
            if ca >= b:
                break
            if ca > cur:
                out.append((cur, ca))
            cur = max(cur, cb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def _measure(intervals: list[tuple]) -> float:
    return sum(b - a for a, b in intervals)


def _clip(intervals: list[tuple], lo: float, hi: float) -> list[tuple]:
    return [(max(a, lo), min(b, hi)) for a, b in intervals
            if b > lo and a < hi]


# -- profile-window rule key -------------------------------------------------

def parse_profile_window(value, default: tuple = (10, 20)) -> tuple:
    """``profile_window`` rule key -> ``(start, stop)`` iteration ints.

    Accepts a 2-sequence, or the string forms the launcher's ``--rule-set
    profile_window=10:20`` hands over (``:``, ``-`` or ``,`` separated).
    Without this, ``tuple("10:20")`` would silently become a 5-char tuple
    and the trace window would never open.
    """
    if value is None:
        return tuple(default)
    if isinstance(value, str):
        for sep in (":", "-", ","):
            if sep in value:
                parts = value.split(sep)
                break
        else:
            raise ValueError(
                f"profile_window={value!r}: expected START:STOP "
                f"(e.g. 10:20)")
        if len(parts) != 2:
            raise ValueError(
                f"profile_window={value!r}: expected exactly two "
                f"iterations, got {len(parts)}")
        return (int(parts[0]), int(parts[1]))
    try:
        start, stop = value
    except (TypeError, ValueError):
        raise ValueError(
            f"profile_window={value!r}: expected a (start, stop) pair")
    start, stop = int(start), int(stop)
    if stop < start:
        raise ValueError(
            f"profile_window={value!r}: stop precedes start")
    return (start, stop)


# -- offline attribution -----------------------------------------------------

def _percentiles(samples: list[float]) -> dict:
    if not samples:
        return {}
    arr = np.asarray(samples, dtype=float) * 1e3
    return {"p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p99_ms": round(float(np.percentile(arr, 99)), 3),
            "mean_ms": round(float(arr.mean()), 3)}


def _rank_mode(spans: list[dict]) -> str:
    names = {s.get("name") for s in spans}
    if "train.step" in names:
        return "train"
    if names & set(_SERVE_SPAN_SEGMENT):
        return "serve"
    return "idle"


def _segment_intervals(spans: list[dict], mode: str) -> dict:
    table = _TRAIN_SPAN_SEGMENT if mode == "train" else _SERVE_SPAN_SEGMENT
    per_seg: dict[str, list] = defaultdict(list)
    for s in spans:
        name = s.get("name", "")
        seg = table.get(name)
        if seg is None and mode == "train" and name.startswith("checkpoint."):
            seg = "checkpoint"
        if seg is None:
            continue
        t0 = float(s["ts"])
        per_seg[seg].append((t0, t0 + float(s.get("dur", 0.0))))
    return {seg: _merge(iv) for seg, iv in per_seg.items()}


def attribute_rank_events(events: list[dict]) -> dict | None:
    """Attribute one rank's events; None when no steps were seen.

    The exact (non-streaming) form — ``tmprof <dir>`` and the streaming
    fold both run through here, so the live gauges and the offline table
    are the same numbers by construction.
    """
    spans = [e for e in events if e.get("kind") == "span"]
    mode = _rank_mode(spans)
    if mode == "idle":
        return None
    step_name = "train.step" if mode == "train" else "serve.decode"
    step_spans = sorted((s for s in spans if s.get("name") == step_name),
                        key=lambda s: float(s["ts"]))
    if not step_spans:
        return None
    # charge only the step-emitting thread: the async checkpoint writer's
    # checkpoint.write overlaps training and must not bill the boundary
    tids = defaultdict(int)
    for s in step_spans:
        tids[s.get("tid")] += 1
    main_tid = max(tids, key=tids.get)
    spans = [s for s in spans if s.get("tid") == main_tid]

    seg_iv = _segment_intervals(spans, mode)
    t0 = min(float(s["ts"]) for s in spans)
    t1 = max(float(s["ts"]) + float(s.get("dur", 0.0)) for s in spans)
    window = [(t0, t1)]

    order = (TRAIN_SEGMENTS[:-1] if mode == "train"
             else ("prefill", "decode"))
    claimed: list[tuple] = []
    claims: dict[str, list] = {}
    for seg in order:
        iv = _subtract(_clip(seg_iv.get(seg, []), t0, t1), claimed)
        claims[seg] = iv
        claimed = _merge(claimed + iv)
    remainder = _subtract(window, claimed)
    if mode == "train":
        claims["host"] = remainder
    else:
        # a gap holding a rollout/rollback instant is the hot-swap stall;
        # every other gap is time the batch spent waiting for work
        marks = sorted(float(e["ts"]) for e in events
                       if e.get("kind") == "instant"
                       and e.get("name") in _ROLLOUT_INSTANTS)
        swap, wait = [], []
        for a, b in remainder:
            hit = any(a <= m < b for m in marks)
            (swap if hit else wait).append((a, b))
        claims["rollout_swap"] = swap
        claims["queue_wait"] = wait

    # per-step decomposition: consecutive windows between step-span ends
    cuts = [t0] + [float(s["ts"]) + float(s.get("dur", 0.0))
                   for s in step_spans]
    per_step: dict[str, list] = {seg: [] for seg in claims}
    walls: list[float] = []
    for lo, hi in zip(cuts, cuts[1:]):
        if hi <= lo:
            continue
        walls.append(hi - lo)
        for seg, iv in claims.items():
            per_step[seg].append(_measure(_clip(iv, lo, hi)))

    segments = {}
    window_s = t1 - t0
    for seg, iv in claims.items():
        total = _measure(iv)
        segments[seg] = {
            "total_s": round(total, 6),
            "share": round(total / window_s, 4) if window_s else 0.0,
            **_percentiles(per_step[seg]),
        }
    dominant = max(segments, key=lambda s: segments[s]["total_s"])
    return {
        "mode": mode,
        "steps": len(step_spans),
        "window_s": round(window_s, 6),
        "wall_step": _percentiles(walls),
        "segments": segments,
        "dominant": {"segment": dominant,
                     "share": segments[dominant]["share"],
                     "verdict": f"{dominant}-bound"},
    }


def attribute_events(events: list[dict]) -> dict:
    """Full-stream attribution -> the ``ATTRIB.json`` ``per_rank`` map."""
    by_rank: dict[int, list] = defaultdict(list)
    for e in events:
        by_rank[int(e.get("rank", 0))].append(e)
    out = {}
    for rank in sorted(by_rank):
        res = attribute_rank_events(
            sorted(by_rank[rank], key=lambda e: float(e.get("ts", 0.0))))
        if res is not None:
            out[str(rank)] = res
    return out


# -- streaming attributor ----------------------------------------------------

class StepAttributor:
    """Feed it every emitted event (``observe``); it folds them into
    bounded cumulative segment totals + per-step sample windows, serves
    ``attr.*`` gauge values at flush boundaries, samples per-device HBM
    watermarks, and publishes ``ATTRIB.json`` atomically.

    Thread-safe: the train loop observes while the Telemetry health
    ticker (or ``close()``) asks for gauges/writes.  Never takes another
    lock while holding its own.
    """

    def __init__(self, directory: str, rank: int = 0):
        self.directory = directory
        self.rank = rank
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._mode = "idle"
        self._steps = 0
        self._window_s = 0.0
        self._totals: dict[str, float] = defaultdict(float)
        self._samples: dict[str, list] = defaultdict(list)
        self._walls: list[float] = []
        self._hbm: dict[str, dict] = {}

    # -- ingestion ----------------------------------------------------------
    def observe(self, event: dict) -> None:
        """O(1) append; a fold every ``_FOLD_EVENTS`` events keeps memory
        bounded on long runs."""
        kind = event.get("kind")
        if kind not in ("span", "instant"):
            return
        with self._lock:
            self._events.append(event)
            if len(self._events) >= _FOLD_EVENTS:
                self._fold()

    def _fold(self) -> None:
        """Attribute the buffered events up to the last complete step and
        merge into the cumulative state.  Call with the lock held."""
        res = attribute_rank_events(
            sorted(self._events, key=lambda e: float(e.get("ts", 0.0))))
        if res is None:
            return
        self._mode = res["mode"]
        self._steps += res["steps"]
        self._window_s += res["window_s"]
        for seg, st in res["segments"].items():
            self._totals[seg] += st["total_s"]
        self._merge_samples(res)
        # drop everything fully inside the folded window; spans still
        # straddling the last step end stay for the next fold
        step_name = "train.step" if res["mode"] == "train" else "serve.decode"
        cut = max((float(e["ts"]) + float(e.get("dur", 0.0))
                   for e in self._events
                   if e.get("kind") == "span"
                   and e.get("name") == step_name), default=None)
        if cut is not None:
            self._events = [
                e for e in self._events
                if float(e.get("ts", 0.0)) + float(e.get("dur", 0.0)) > cut]

    def _merge_samples(self, res: dict) -> None:
        walls = res.get("wall_step") or {}
        if walls:
            self._walls.append(walls.get("p50_ms", 0.0) / 1e3)
        for seg, st in res["segments"].items():
            if "p50_ms" in st:
                self._samples[seg].append(st["p50_ms"] / 1e3)
        for lst in (*self._samples.values(), self._walls):
            if len(lst) > _SAMPLE_WINDOW:
                del lst[: len(lst) - _SAMPLE_WINDOW]

    # -- memory watermarks --------------------------------------------------
    def sample_memory(self) -> dict[str, float]:
        """Sample per-device HBM stats (None-safe — empty on CPU) into the
        running watermarks; -> worst-device gauge values keyed by the
        registered ``prof.hbm_*`` names."""
        stats = per_device_memory_stats()
        if not stats:
            return {}
        gauges: dict[str, float] = {}
        with self._lock:
            for dev, st in stats.items():
                w = self._hbm.setdefault(str(dev), {})
                live = st.get("bytes_in_use")
                if live is not None:
                    w["bytes_in_use"] = int(live)
                    w["peak_bytes_in_use"] = max(
                        int(st.get("peak_bytes_in_use", live)),
                        w.get("peak_bytes_in_use", 0))
                if "bytes_limit" in st:
                    w["bytes_limit"] = int(st["bytes_limit"])
            peaks = [w.get("peak_bytes_in_use", 0)
                     for w in self._hbm.values()]
            lives = [w.get("bytes_in_use", 0) for w in self._hbm.values()]
            limits = [w["bytes_limit"] for w in self._hbm.values()
                      if "bytes_limit" in w]
        if peaks:
            gauges[PROF_GAUGES[0]] = float(max(peaks))
        if lives:
            gauges[PROF_GAUGES[1]] = float(max(lives))
        if limits:
            gauges[PROF_GAUGES[2]] = float(min(limits))
        return gauges

    # -- readout ------------------------------------------------------------
    def _result_locked(self) -> dict | None:
        """Cumulative + still-buffered view.  Call with the lock held."""
        live = attribute_rank_events(
            sorted(self._events, key=lambda e: float(e.get("ts", 0.0))))
        if live is None and self._steps == 0:
            return None
        if self._steps == 0:
            return live
        if live is None:
            live = {"mode": self._mode, "steps": 0, "window_s": 0.0,
                    "segments": {}, "wall_step": {}}
        segs = set(self._totals) | set(live["segments"])
        segments = {}
        window_s = self._window_s + live["window_s"]
        for seg in segs:
            total = self._totals.get(seg, 0.0) + live["segments"].get(
                seg, {}).get("total_s", 0.0)
            samples = list(self._samples.get(seg, ()))
            live_p50 = live["segments"].get(seg, {}).get("p50_ms")
            if live_p50 is not None:
                samples.append(live_p50 / 1e3)
            segments[seg] = {
                "total_s": round(total, 6),
                "share": round(total / window_s, 4) if window_s else 0.0,
                **_percentiles(samples),
            }
        dominant = max(segments, key=lambda s: segments[s]["total_s"])
        walls = list(self._walls)
        if live.get("wall_step", {}).get("p50_ms") is not None:
            walls.append(live["wall_step"]["p50_ms"] / 1e3)
        return {
            "mode": live["mode"],
            "steps": self._steps + live["steps"],
            "window_s": round(window_s, 6),
            "wall_step": _percentiles(walls),
            "segments": segments,
            "dominant": {"segment": dominant,
                         "share": segments[dominant]["share"],
                         "verdict": f"{dominant}-bound"},
        }

    def gauges(self) -> dict[str, float]:
        """Registered ``attr.*`` gauge values (per-step p50 ms per
        segment) for the flush boundary; empty before the first step."""
        with self._lock:
            res = self._result_locked()
        if res is None:
            return {}
        out: dict[str, float] = {}
        for seg, st in res["segments"].items():
            name = ATTR_GAUGE_BY_SEGMENT.get(seg)
            if name is not None and "p50_ms" in st:
                out[name] = st["p50_ms"]
        p50 = res.get("wall_step", {}).get("p50_ms")
        if p50 is not None:
            out[ATTR_GAUGE_BY_SEGMENT["step"]] = p50
        return out

    def result(self) -> dict | None:
        with self._lock:
            return self._result_locked()

    def write(self, path: str | None = None) -> str | None:
        """Atomically publish ``ATTRIB.json`` (tmp + ``os.replace`` — a
        reader never sees a torn file); None when no steps ran."""
        with self._lock:
            res = self._result_locked()
            hbm = {d: dict(w) for d, w in self._hbm.items()}
        if res is None:
            return None
        path = path or os.path.join(self.directory, ATTRIB_FILENAME)
        payload = {
            # wall stamp: the perf ledger correlates runs across processes
            "updated": time.time(),  # lint: wall-ok — cross-process stamp
            "pid": os.getpid(),
            "rank": self.rank,
            "per_rank": {str(self.rank): res},
        }
        if hbm:
            payload["hbm"] = hbm
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path


def read_attrib(directory: str) -> dict | None:
    """Parse ``<directory>/ATTRIB.json``; None when absent/unreadable."""
    path = os.path.join(directory, ATTRIB_FILENAME)
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def format_attribution(per_rank: dict) -> str:
    """The ``tmprof`` attribution table: one block per rank, one line per
    segment, shares + per-step percentiles, dominant-term verdict."""
    lines = []
    for rank, res in sorted(per_rank.items()):
        lines.append(f"rank {rank}  [{res['mode']}]  steps {res['steps']}  "
                     f"window {res['window_s']:.3f}s")
        wall = res.get("wall_step") or {}
        if wall:
            lines.append(f"  step wall: p50 {wall.get('p50_ms', 0):.1f}ms  "
                         f"p99 {wall.get('p99_ms', 0):.1f}ms")
        order = TRAIN_SEGMENTS if res["mode"] == "train" else SERVE_SEGMENTS
        total = 0.0
        for seg in order:
            st = res["segments"].get(seg)
            if st is None:
                continue
            total += st["total_s"]
            pct = ("" if "p50_ms" not in st else
                   f"  p50 {st['p50_ms']:8.1f}ms  p99 {st['p99_ms']:8.1f}ms")
            lines.append(f"  {seg:<12} {st['total_s']:9.3f}s "
                         f"{st['share']:7.1%}{pct}")
        lines.append(f"  {'sum':<12} {total:9.3f}s "
                     f"{total / res['window_s'] if res['window_s'] else 0:7.1%}")
        dom = res["dominant"]
        lines.append(f"  verdict: {dom['verdict']} "
                     f"({dom['segment']} {dom['share']:.1%} of window)")
    return "\n".join(lines)
