"""Streaming run-health detectors over the live event stream (ISSUE 13).

PR 1's telemetry is write-only: per-rank JSONL sinks aggregated *after*
the run ends, so a hung job burns its fleet lease until a blunt timeout
and a crashed one leaves only an exit code.  :class:`HealthMonitor` turns
the same event stream into *in-flight* typed verdicts:

- ``hang`` — the arrival-clock deadline: event ``ts`` values are
  per-process ``perf_counter`` epochs (not comparable across processes),
  so liveness is judged by *when events arrive on the monitor's own
  clock*.  Armed only after ``hang_warmup_steps`` ``train.step`` spans
  (compile-heavy first steps never trip it), suspended between
  ``train.boundary`` begin/end instants (validate/checkpoint are
  legitimately span-free), disarmed at ``session_end``.
- ``straggler`` — the incremental form of ``aggregate.summarize_events``'s
  step-skew math: per-rank ``train.step`` duration windows, skew over the
  steps every rank reported, worst-rank mean vs the fleet mean.  A
  single-process monitor only ever sees its own rank; the detector earns
  its keep when ``tmhealth`` replays a whole directory of ranks.
- ``loss`` — EWMA z-score on the ``loss`` tag of ``train.step`` spans;
  a non-finite loss is an immediate ``critical``, a spike past
  ``loss_z`` standard deviations is a ``warn``.
- ``throughput`` — recent median step duration vs a rolling baseline
  median; a ``throughput_factor`` slowdown is a ``warn``.
- ``checkpoint`` — checkpoints were happening and then stopped: once a
  ``checkpoint.*`` span has been seen, steps advancing for longer than
  ``checkpoint_deadline_s`` without another is a ``warn``.
- ``slo`` — serving SLO breach: p99 of the ``serve.ttft_ms`` histogram
  (carried by ``metrics`` flush events) above ``slo_ttft_p99_ms``; at
  ``slo_critical_factor`` x the SLO the verdict turns *critical*, which
  the serving rollout watcher's probation window treats as the
  roll-back-now signal (ISSUE 14).
- ``async_staleness`` — the async rules' graceful-degradation witness
  (ISSUE 20), fed by the per-round ``easgd.exchange`` / ``gosgd.round``
  instants: *warn* when staleness skews past the rule's expected cadence
  or the exchange wall-interval stretches past the rolling median for
  ``async_min_rounds`` consecutive rounds (a straggler the rule is
  absorbing — degraded, not broken); *critical* only when the relative
  center drift blows past ``async_drift_critical`` (the elastic coupling
  is no longer bounding divergence — correctness, not throughput).

Verdicts are written atomically to ``HEALTH.json`` in the telemetry
directory by the owning :class:`~theanompi_tpu.telemetry.core.Telemetry`'s
ticker thread; ``resilience/supervisor.py`` and ``fleet/scheduler.py``
consume the file with plain ``json`` (no import of this module needed in
the stdlib-only supervisor).  Off means off: no ``Telemetry`` → no
monitor; a ``Telemetry`` with ``health=None`` makes zero calls here.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

HEALTH_FILENAME = "HEALTH.json"

SEV_OK = "ok"
SEV_WARN = "warn"
SEV_CRITICAL = "critical"


@dataclass
class Verdict:
    """One detector's current judgement of the run."""

    detector: str
    severity: str   # ok | warn | critical
    reason: str
    step: int | None = None
    fields: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        out = {"detector": self.detector, "severity": self.severity,
               "reason": self.reason}
        if self.step is not None:
            out["step"] = int(self.step)
        if self.fields:
            out["fields"] = self.fields
        return out


@dataclass
class HealthConfig:
    """Detector thresholds.  Every deadline is in seconds on the
    monitor's own clock (event *arrival*, never event ``ts``)."""

    tick_s: float = 1.0
    hang_deadline_s: float = 60.0
    hang_warmup_steps: int = 3
    window: int = 64                  # per-rank step-duration window
    straggler_ratio: float = 1.5
    straggler_min_steps: int = 4
    loss_z: float = 6.0
    loss_warmup: int = 8
    loss_ewma_alpha: float = 0.1
    throughput_factor: float = 2.0
    throughput_min_steps: int = 16
    throughput_recent: int = 8
    checkpoint_deadline_s: float = 600.0
    slo_ttft_p99_ms: float | None = None
    #: ISSUE 14: a p99 at or past ``slo_critical_factor`` x the SLO is a
    #: CRITICAL verdict (not just a warn) — the serving rollout watcher's
    #: probation window rolls back on it
    slo_critical_factor: float = 2.0
    #: ISSUE 16: watch a ``PERF_LEDGER.jsonl`` for typed regression
    #: verdicts (``telemetry/ledger.py``) — None keeps the detector off.
    #: The file is re-checked only when its mtime moves, so an armed
    #: detector costs one ``stat`` per tick.
    perf_ledger_path: str | None = None
    perf_tolerance: float = 0.10
    #: ISSUE 20 async_staleness thresholds: a round is BAD when its
    #: staleness reaches ``async_staleness_factor`` x the rule's expected
    #: cadence, or its wall interval ``async_stretch_factor`` x the
    #: rolling median of previous rounds; ``async_min_rounds`` consecutive
    #: bad rounds make a warn.  Drift at/past ``async_drift_critical``
    #: (relative ``||p_i - center|| / ||center||``) is critical outright.
    async_staleness_factor: float = 3.0
    async_stretch_factor: float = 2.5
    async_min_rounds: int = 2
    async_drift_critical: float = 5.0


def _median(xs) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class HealthMonitor:
    """Feed it every emitted event (``observe``); poll it (``tick``).

    Thread-safe: the train loop observes while the Telemetry ticker
    thread ticks and writes.  ``tick`` returns the verdicts that
    *changed* since the last tick so the caller can mirror transitions
    into the event stream without holding the monitor's lock.
    """

    def __init__(self, directory: str, config: HealthConfig | None = None,
                 rank: int = 0, clock=time.perf_counter):
        self.directory = directory
        self.config = config or HealthConfig()
        self.rank = rank
        self._clock = clock
        self._lock = threading.Lock()
        self._verdicts: dict[str, Verdict] = {}
        self._published: dict[str, tuple] = {}  # detector -> (sev, reason)
        # hang state
        self._last_arrival = clock()
        self._steps = 0
        self._last_step: int | None = None
        self._boundary_depth = 0
        self._ended = False
        # straggler state: rank -> {step -> dur}, bounded per rank
        self._step_durs: dict[int, dict[int, float]] = {}
        # loss EWMA state
        self._loss_n = 0
        self._loss_mean = 0.0
        self._loss_var = 0.0
        # throughput state
        self._durs: deque = deque(maxlen=self.config.window)
        # checkpoint state
        self._last_ckpt: float | None = None
        self._steps_at_ckpt = 0
        # perf-ledger state (ISSUE 16): mtime cache so an unchanged
        # ledger costs one stat per tick, not a reparse
        self._perf_mtime: float | None = None
        # async-rule state (ISSUE 20): consecutive bad-round streak
        self._async_bad_rounds = 0

    # -- ingestion -----------------------------------------------------------
    def observe(self, event: dict, now: float | None = None) -> None:
        """Feed one emitted event.  O(window) worst case, dict updates
        typically — safe on the hot path."""
        now = self._clock() if now is None else now
        with self._lock:
            self._last_arrival = now
            kind = event.get("kind")
            name = event.get("name")
            if kind == "meta" and name == "session_end":
                self._ended = True
                self._set("hang", SEV_OK, "session ended cleanly")
            elif kind == "instant" and name == "train.boundary":
                if event.get("phase") == "begin":
                    self._boundary_depth += 1
                else:
                    self._boundary_depth = max(0, self._boundary_depth - 1)
            elif kind == "span" and name == "train.step":
                self._observe_step(event)
            elif kind == "instant" and name in ("easgd.exchange",
                                                "gosgd.round"):
                self._observe_async(event)
            elif name is not None and str(name).startswith("checkpoint."):
                self._last_ckpt = now
                self._steps_at_ckpt = self._steps
                self._set("checkpoint", SEV_OK, "checkpoint activity")
            elif kind == "metrics":
                self._observe_metrics(event)

    def _observe_step(self, event: dict) -> None:
        cfg = self.config
        self._steps += 1
        step = event.get("step")
        self._last_step = int(step) if step is not None else self._last_step
        dur = float(event.get("dur", 0.0))
        rank = int(event.get("rank", 0))
        if step is not None:
            durs = self._step_durs.setdefault(rank, {})
            durs[int(step)] = dur
            if len(durs) > cfg.window:
                del durs[min(durs)]
            self._eval_straggler()
        self._durs.append(dur)
        self._eval_throughput()
        if "loss" in event:
            self._eval_loss(float(event["loss"]))
        # a step arriving clears a previous hang verdict: the run moved
        if self._steps >= cfg.hang_warmup_steps:
            self._set("hang", SEV_OK, "events flowing")

    def _observe_metrics(self, event: dict) -> None:
        cfg = self.config
        if cfg.slo_ttft_p99_ms is None:
            return
        p99 = (event.get("histograms") or {}).get("serve.ttft_ms",
                                                  {}).get("p99")
        if p99 is None:
            return
        if p99 > cfg.slo_ttft_p99_ms:
            critical = p99 >= cfg.slo_ttft_p99_ms * cfg.slo_critical_factor
            self._set("slo", SEV_CRITICAL if critical else SEV_WARN,
                      f"serve.ttft_ms p99 {p99:.1f}ms breaches SLO "
                      f"{cfg.slo_ttft_p99_ms:.1f}ms"
                      + (f" by >= {cfg.slo_critical_factor:g}x"
                         if critical else ""),
                      fields={"p99_ms": round(float(p99), 3),
                              "slo_ms": cfg.slo_ttft_p99_ms})
        else:
            self._set("slo", SEV_OK, "serve.ttft_ms p99 within SLO")

    def _observe_async(self, event: dict) -> None:
        """ISSUE 20: one ``easgd.exchange`` / ``gosgd.round`` instant per
        exchange round.  Severity contract (the chaos acceptance leans on
        it): a straggler the rule absorbs is at most a WARN — sustained
        staleness skew or interval stretch says "degraded, still
        converging"; only a center-drift blow-up (the elastic coupling no
        longer bounds divergence, a correctness signal) is CRITICAL."""
        cfg = self.config
        step = event.get("step")
        step = int(step) if step is not None else self._last_step
        name = event.get("name")
        drift = event.get("drift")
        if drift is not None and float(drift) >= cfg.async_drift_critical:
            self._set("async_staleness", SEV_CRITICAL,
                      f"center drift {float(drift):.3g} at/past "
                      f"{cfg.async_drift_critical:g} — the elastic "
                      f"coupling is not bounding worker divergence",
                      step=step,
                      fields={"drift": round(float(drift), 6),
                              "critical_at": cfg.async_drift_critical})
            return
        staleness = float(event.get("staleness", 0.0) or 0.0)
        expected = max(float(event.get("expected", 1.0) or 1.0), 1.0)
        stretch = float(event.get("stretch", 0.0) or 0.0)
        stale_skew = staleness >= expected * cfg.async_staleness_factor
        stretched = stretch >= cfg.async_stretch_factor
        if stale_skew or stretched:
            self._async_bad_rounds += 1
        else:
            self._async_bad_rounds = 0
        fields = {"staleness": staleness, "expected": expected,
                  "stretch": round(stretch, 3),
                  "bad_rounds": self._async_bad_rounds}
        if drift is not None:
            fields["drift"] = round(float(drift), 6)
        if self._async_bad_rounds >= cfg.async_min_rounds:
            why = (f"staleness {staleness:g} is >= "
                   f"{cfg.async_staleness_factor:g}x the expected "
                   f"cadence {expected:g}" if stale_skew else
                   f"exchange interval stretched {stretch:.2f}x the "
                   f"rolling median")
            self._set("async_staleness", SEV_WARN,
                      f"{name}: {why} for {self._async_bad_rounds} "
                      f"consecutive round(s) — straggler being absorbed",
                      step=step, fields=fields)
        else:
            self._set("async_staleness", SEV_OK,
                      "async exchange cadence healthy", step=step,
                      fields=fields)

    # -- detectors -----------------------------------------------------------
    def _eval_straggler(self) -> None:
        cfg = self.config
        ranks = [r for r, d in self._step_durs.items() if d]
        if len(ranks) < 2:
            return
        common = set.intersection(*(set(self._step_durs[r]) for r in ranks))
        if len(common) < cfg.straggler_min_steps:
            return
        means = {r: sum(self._step_durs[r].values())
                 / len(self._step_durs[r]) for r in ranks}
        fleet = sum(means.values()) / len(means)
        worst = max(means, key=means.get)
        ratio = means[worst] / fleet if fleet else 0.0
        skews = [max(self._step_durs[r][s] for r in ranks)
                 - min(self._step_durs[r][s] for r in ranks)
                 for s in common]
        fields = {
            "rank": worst,
            "mean_step_ms": round(means[worst] * 1e3, 3),
            "vs_fleet_mean": round(ratio, 3),
            "step_skew_ms": {"mean": round(_median(skews) * 1e3, 3),
                             "max": round(max(skews) * 1e3, 3),
                             "steps_compared": len(skews)},
        }
        if ratio >= cfg.straggler_ratio:
            self._set("straggler", SEV_WARN,
                      f"rank {worst} runs {ratio:.2f}x the fleet mean "
                      f"step time", fields=fields)
        else:
            self._set("straggler", SEV_OK,
                      f"skew within {cfg.straggler_ratio}x", fields=fields)

    def _eval_loss(self, x: float) -> None:
        cfg = self.config
        if not math.isfinite(x):
            self._set("loss", SEV_CRITICAL, f"non-finite loss {x!r}",
                      step=self._last_step)
            return
        if self._loss_n >= cfg.loss_warmup:
            sd = math.sqrt(max(self._loss_var, 0.0))
            z = (x - self._loss_mean) / sd if sd > 1e-12 else 0.0
            if z > cfg.loss_z:
                self._set("loss", SEV_WARN,
                          f"loss {x:.4g} is {z:.1f} sigma above the EWMA "
                          f"{self._loss_mean:.4g}",
                          step=self._last_step,
                          fields={"z": round(z, 2),
                                  "ewma": round(self._loss_mean, 6)})
            else:
                self._set("loss", SEV_OK, "loss within band",
                          step=self._last_step)
        self._loss_n += 1
        diff = x - self._loss_mean
        incr = cfg.loss_ewma_alpha * diff
        self._loss_mean += incr
        self._loss_var = (1 - cfg.loss_ewma_alpha) * (self._loss_var
                                                      + diff * incr)

    def _eval_throughput(self) -> None:
        cfg = self.config
        n = len(self._durs)
        if n < max(cfg.throughput_min_steps, cfg.throughput_recent + 2):
            return
        durs = list(self._durs)
        recent = _median(durs[-cfg.throughput_recent:])
        baseline = _median(durs[:-cfg.throughput_recent])
        fields = {"recent_ms": round(recent * 1e3, 3),
                  "baseline_ms": round(baseline * 1e3, 3)}
        if baseline > 0 and recent > baseline * cfg.throughput_factor:
            self._set("throughput", SEV_WARN,
                      f"recent step time {recent * 1e3:.1f}ms is "
                      f"{recent / baseline:.2f}x the rolling baseline",
                      step=self._last_step, fields=fields)
        else:
            self._set("throughput", SEV_OK, "throughput holding baseline",
                      step=self._last_step, fields=fields)

    def _eval_perf(self) -> None:
        """ISSUE 16 perf detector: mirror the ledger's typed regression
        verdicts as a live ``warn``.  The check is the lock-free
        :func:`~theanompi_tpu.telemetry.ledger.check_ledger` read — no
        ledger lock ever nests inside the health lock."""
        cfg = self.config
        if cfg.perf_ledger_path is None:
            return
        try:
            mtime = os.path.getmtime(cfg.perf_ledger_path)
        except OSError:
            return  # no ledger yet — the detector stays silent
        if mtime == self._perf_mtime:
            return
        self._perf_mtime = mtime
        from theanompi_tpu.telemetry.ledger import check_ledger, regressions

        bad = regressions(check_ledger(cfg.perf_ledger_path,
                                       tolerance=cfg.perf_tolerance))
        if bad:
            worst = max(bad, key=lambda v: abs(v.get("delta_pct") or 0.0))
            self._set("perf", SEV_WARN,
                      f"{len(bad)} perf metric(s) regressed past "
                      f"{cfg.perf_tolerance:.0%}: {worst['metric']} "
                      f"{worst['delta_pct']:+.1f}% vs trailing median",
                      fields={"regressed": [v["metric"] for v in bad],
                              "worst_delta_pct": worst["delta_pct"],
                              "tolerance_pct": worst["tolerance_pct"]})
        else:
            self._set("perf", SEV_OK, "no perf regressions in ledger")

    def _set(self, detector: str, severity: str, reason: str,
             step: int | None = None, fields: dict | None = None) -> None:
        self._verdicts[detector] = Verdict(
            detector, severity, reason,
            step=step if step is not None else self._last_step,
            fields=fields or {})

    # -- polling -------------------------------------------------------------
    def tick(self, now: float | None = None) -> list[Verdict]:
        """Evaluate the time-based detectors; -> verdicts that changed
        severity-or-reason since the last tick (for event mirroring)."""
        now = self._clock() if now is None else now
        cfg = self.config
        with self._lock:
            stalled = now - self._last_arrival
            if (not self._ended and self._boundary_depth == 0
                    and self._steps >= cfg.hang_warmup_steps
                    and stalled > cfg.hang_deadline_s):
                self._set("hang", SEV_CRITICAL,
                          f"no events for {stalled:.1f}s "
                          f"(deadline {cfg.hang_deadline_s:g}s)",
                          fields={"stalled_s": round(stalled, 1),
                                  "deadline_s": cfg.hang_deadline_s})
            if (self._last_ckpt is not None and not self._ended
                    and self._steps > self._steps_at_ckpt
                    and now - self._last_ckpt > cfg.checkpoint_deadline_s):
                self._set("checkpoint", SEV_WARN,
                          f"steps advanced but no checkpoint for "
                          f"{now - self._last_ckpt:.0f}s",
                          fields={"since_s": round(now - self._last_ckpt, 1),
                                  "deadline_s": cfg.checkpoint_deadline_s})
            self._eval_perf()
            changed = []
            for det, v in self._verdicts.items():
                key = (v.severity, v.reason)
                if self._published.get(det, (SEV_OK, None))[0] != v.severity:
                    changed.append(v)
                self._published[det] = key
            return changed

    def verdicts(self) -> list[dict]:
        with self._lock:
            return [v.to_dict() for v in self._verdicts.values()]

    def worst_severity(self) -> str:
        order = {SEV_OK: 0, SEV_WARN: 1, SEV_CRITICAL: 2}
        with self._lock:
            sevs = [v.severity for v in self._verdicts.values()]
        return max(sevs, key=lambda s: order.get(s, 0), default=SEV_OK)

    # -- persistence ---------------------------------------------------------
    def write(self, path: str | None = None) -> str:
        """Atomically publish ``HEALTH.json`` (tmp + ``os.replace`` — a
        reader never sees a torn file)."""
        path = path or os.path.join(self.directory, HEALTH_FILENAME)
        payload = {
            # wall stamp: external consumers (supervisor, tmhealth, a
            # human) correlate it with their own clocks
            "updated": time.time(),  # lint: wall-ok — cross-process stamp
            "pid": os.getpid(),
            "rank": self.rank,
            "steps": self._steps,
            "verdicts": self.verdicts(),
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path


def read_health(directory: str) -> dict | None:
    """Parse ``<directory>/HEALTH.json``; None when absent/unreadable."""
    path = os.path.join(directory, HEALTH_FILENAME)
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def hung_verdict(health: dict | None) -> dict | None:
    """The critical ``hang`` verdict out of a ``HEALTH.json`` payload, or
    None.  Shared predicate for the supervisor/fleet consumers (they read
    the file with plain ``json`` but agree on the shape through this)."""
    if not health:
        return None
    for v in health.get("verdicts", ()):
        if (isinstance(v, dict) and v.get("detector") == "hang"
                and v.get("severity") == SEV_CRITICAL):
            return v
    return None


def replay_events(events, config: HealthConfig | None = None,
                  directory: str = "") -> HealthMonitor:
    """Run the streaming detectors over already-recorded events (the
    ``tmhealth`` offline path).  Arrival-clock detectors (hang) cannot
    fire meaningfully in a replay — the caller judges staleness from
    sink-file mtimes instead."""
    mon = HealthMonitor(directory, config)
    t = 0.0
    for ev in events:
        t += 1e-9  # synthetic strictly-increasing arrival clock
        mon.observe(ev, now=t)
    mon.tick(now=t)
    return mon
