"""``python -m theanompi_tpu.telemetry`` — the ``tmhealth`` CLI."""

import sys

from theanompi_tpu.telemetry.cli import main

if __name__ == "__main__":
    sys.exit(main())
