"""Telemetry front-end: spans, instants, counters, gauges, metric flushes.

Design rules (ISSUE 1):

- **Off means off.**  Nothing in this module runs on the hot path unless a
  ``Telemetry`` was explicitly constructed and handed to the trainer; the
  integration points all guard with ``if telemetry is not None`` so a
  disabled run makes zero telemetry calls (asserted by the tests).
- **Honest under async dispatch.**  A span around jax work measures
  *dispatch* unless something fences.  Spans accept the same optional
  ``fence`` the Recorder uses: ``end(fence=x)`` blocks on the array before
  stamping the close time.  The Recorder integration inherits its existing
  fence discipline unchanged — the recorder blocks first, then reports the
  segment here, so recorder spans and recorder histories are the same
  numbers by construction.
- **Monotonic time.**  All timestamps are ``time.perf_counter()``; the one
  wall-clock anchor is an ISO string in the session ``meta`` event.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from datetime import datetime, timezone

# analysis.interleave is stdlib-only and sits at the bottom of the
# import DAG — the one non-telemetry import the leaf wall permits
from theanompi_tpu.analysis.interleave import sp
from theanompi_tpu.telemetry.metrics import MetricsRegistry
from theanompi_tpu.telemetry.sink import EventSink


class Span:
    """Context manager stamping one complete span event on exit.

    Emitted at close (Chrome ``ph: "X"`` style: start + duration), so
    nesting in Perfetto comes from containment on the thread track — no
    begin/end pairing to corrupt if a run dies mid-span.
    """

    __slots__ = ("_tel", "name", "tags", "t0", "_closed")

    def __init__(self, tel: "Telemetry", name: str, tags: dict):
        self._tel = tel
        self.name = name
        self.tags = tags
        self.t0 = 0.0
        self._closed = False

    def __enter__(self) -> "Span":
        self.t0 = time.perf_counter()
        return self

    def end(self, fence=None) -> float:
        """Close + emit once; -> duration.  Idempotent, so a manual
        fence-aware ``end(fence=x)`` inside a ``with`` block does not
        double-emit when ``__exit__`` runs."""
        if self._closed:
            return 0.0
        self._closed = True
        if fence is not None:
            import jax

            jax.block_until_ready(fence)
        dur = time.perf_counter() - self.t0
        self._tel.emit_span(self.name, self.t0, dur, **self.tags)
        return dur

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and not self._closed:
            self.tags = {**self.tags, "error": exc_type.__name__}
        self.end()


class Telemetry:
    """One per process: owns the rank's sink and metrics registry."""

    def __init__(self, directory: str, rank: int | None = None,
                 host: str | None = None, max_bytes: int = 32 * 2**20,
                 keep: int = 3, health=None, flight_recorder: int = 0,
                 profile=None):
        if rank is None:
            try:
                import jax

                rank = jax.process_index()
            except Exception:  # lint: swallow-ok — pre-init default rank
                rank = 0
        self.rank = rank
        self.host = host or socket.gethostname()
        self.directory = directory
        self.sink = EventSink(directory, rank=rank, max_bytes=max_bytes,
                              keep=keep)
        self.metrics = MetricsRegistry()
        # ISSUE 13 live health: both default OFF — a Telemetry constructed
        # the pre-13 way makes zero health/flight-recorder calls (same
        # off-means-off contract the trainer holds for telemetry itself).
        # ``health`` accepts True (defaults), a HealthConfig, or a dict of
        # HealthConfig overrides; ``flight_recorder`` is the ring capacity.
        self.flight = None
        self.health = None
        self.prof = None
        self._health_stop: threading.Event | None = None
        self._health_thread: threading.Thread | None = None
        if flight_recorder:
            from theanompi_tpu.telemetry.flight_recorder import FlightRecorder

            self.flight = FlightRecorder(directory,
                                         capacity=int(flight_recorder),
                                         rank=rank)
        if health:
            from theanompi_tpu.telemetry.health import (HealthConfig,
                                                        HealthMonitor)

            cfg = (health if isinstance(health, HealthConfig)
                   else HealthConfig(**health) if isinstance(health, dict)
                   else HealthConfig())
            self.health = HealthMonitor(directory, cfg, rank=rank)
            self._health_stop = threading.Event()
            self._health_thread = threading.Thread(
                target=self._health_loop, name="telemetry-health",
                daemon=True)
            self._health_thread.start()
        if profile:
            # ISSUE 16 step attribution: same off-means-off contract — a
            # Telemetry constructed the pre-16 way makes zero calls here
            from theanompi_tpu.telemetry.profile import StepAttributor

            self.prof = StepAttributor(directory, rank=rank)
        self.emit("meta", "session",
                  wall_time=datetime.now(timezone.utc).isoformat(),
                  host=self.host, pid=os.getpid())

    # -- raw emission --------------------------------------------------------
    def emit(self, kind: str, name: str, ts: float | None = None,
             **fields) -> None:
        event = {"ts": time.perf_counter() if ts is None else ts,
                 "kind": kind, "name": name, "rank": self.rank}
        event.update(fields)
        self.sink.emit(event)
        if self.flight is not None:
            self.flight.record(event)
        if self.health is not None:
            self.health.observe(event)
        if self.prof is not None:
            self.prof.observe(event)

    def emit_span(self, name: str, t0: float, dur: float, **tags) -> None:
        self.emit("span", name, ts=t0, dur=dur,
                  tid=threading.get_ident(), **tags)

    # -- user surface --------------------------------------------------------
    def span(self, name: str, **tags) -> Span:
        return Span(self, name, tags)

    def instant(self, name: str, **fields) -> None:
        self.emit("instant", name, **fields)

    def count(self, name: str, value: float = 1.0, emit: bool = False,
              **tags) -> None:
        """Increment a counter.  By default accumulation-only (no I/O) —
        totals ride the next ``flush_metrics``; ``emit=True`` also writes a
        counter event now (used for one-per-exchange accounting)."""
        total = self.metrics.count(name, value)
        if emit:
            self.emit("counter", name, value=value, total=total, **tags)

    def gauge(self, name: str, value: float, **tags) -> None:
        """Set a gauge: registry (for the snapshot) + one gauge event.
        Gauges are set at flush boundaries, never per-iteration, so the
        event write is off the hot path."""
        self.metrics.gauge(name, value)
        self.emit("gauge", name, value=float(value), **tags)

    def observe(self, name: str, value: float) -> None:
        self.metrics.observe(name, value)

    def flush_metrics(self, step: int | None = None, **extra) -> None:
        """One ``metrics`` event carrying the registry snapshot."""
        snap = self.metrics.snapshot()
        if step is not None:
            snap["step"] = step
        snap.update(extra)
        self.emit("metrics", "metrics", **snap)

    def profile_flush(self, step: int | None = None) -> None:
        """Publish the attribution gauges + HBM watermarks and refresh
        ``ATTRIB.json`` — called at the trainer's fenced print boundary
        (ISSUE 16).  No-op unless ``profile=`` was configured.

        Gauge values are computed before any emission, so the attributor
        never holds its lock across an emit (lock-order discipline: no
        nesting with the sink's lock).
        """
        if self.prof is None:
            return
        gauges = dict(self.prof.gauges())
        gauges.update(self.prof.sample_memory())
        for name, value in gauges.items():
            self.gauge(name, value, step=step)
        try:
            self.prof.write()
        except OSError:
            pass  # lint: swallow-ok — advisory file; next flush retries

    def export_chrome_trace(self, path: str | None = None) -> str:
        """Write this rank's events as a Chrome trace-event JSON file."""
        from theanompi_tpu.telemetry.chrome_trace import export_chrome_trace
        from theanompi_tpu.telemetry.sink import sink_files

        path = path or os.path.join(self.directory,
                                    f"trace-rank{self.rank:05d}.json")
        return export_chrome_trace(
            sink_files(self.directory, rank=self.rank), path)

    # -- live health (ISSUE 13) ----------------------------------------------
    def _health_loop(self) -> None:
        """Daemon ticker: exists only when health is enabled.  Runs the
        time-based detectors and republishes ``HEALTH.json`` even while
        the main thread is wedged — which is exactly when the hang
        verdict matters."""
        while not self._health_stop.wait(self.health.config.tick_s):
            self._health_tick()

    def _health_tick(self) -> None:
        from theanompi_tpu.telemetry.metrics import HEALTH_INSTANTS

        sp("health.tick")
        changed = self.health.tick()
        for v in changed:
            # mirror severity *transitions* into the event stream (the
            # tick released the monitor's lock before we emit, so the
            # observe() this triggers cannot deadlock)
            self.instant(HEALTH_INSTANTS[1], detector=v.detector,
                         severity=v.severity, reason=v.reason)
        if self.flight is not None and any(
                v.detector == "hang" and v.severity == "critical"
                for v in changed):
            # last words while still alive: the supervisor answers a
            # critical hang with SIGKILL, which a wedged process cannot
            # dump under — so the ticker dumps the moment the verdict
            # turns, leaving the blackbox the harvest expects
            try:
                self.flight.dump("hang", health=self.health.verdicts())
            except OSError:
                pass  # lint: swallow-ok — advisory file; verdict stands
        try:
            self.health.write()
        except OSError:
            pass  # lint: swallow-ok — advisory file; next tick retries

    def close(self) -> None:
        sp("health.close")
        if self._health_thread is not None:
            self._health_stop.set()
            self._health_thread.join(timeout=5.0)
            self._health_thread = None
        self.flush_metrics()
        self.emit("meta", "session_end")
        if self.prof is not None:
            # final attribution summary: the per-run artifact the perf
            # ledger ingests (written before the sink closes so the last
            # buffered spans are counted)
            try:
                self.prof.write()
            except OSError:
                pass  # lint: swallow-ok — advisory file at shutdown
        if self.health is not None:
            # final publish AFTER session_end so the file's last word is
            # the disarmed, end-of-run state
            self.health.tick()
            try:
                self.health.write()
            except OSError:
                pass  # lint: swallow-ok — advisory file at shutdown
        self.sink.close()
