"""theanompi_tpu — a TPU-native distributed training framework.

A from-scratch rebuild of the *capabilities* of Theano-MPI
(Sentient07/Theano-MPI; see /root/repo/SURVEY.md — the reference mount was
empty during the survey, so reference paths cited throughout this package are
the *expected upstream* paths from SURVEY.md §1–2, tagged "unverified"):

- pluggable parameter-exchange **rules**: ``BSP`` (synchronous all-reduce),
  ``EASGD`` (elastic-averaging parameter server), ``GOSGD`` (gossip)
  — reference (unverified): ``theanompi/__init__.py``;
- a strategy-pluggable **exchanger** — reference: ``theanompi/lib/exchanger.py``
  + ``exchanger_strategy.py`` (``ar``/``asa32``/``asa16``/``nccl32``/``nccl16``)
  — here re-expressed as XLA collectives (``psum``/``ppermute``) over ICI with
  bf16-compressed variants;
- a **model zoo** (AlexNet, GoogLeNet, VGG16, ResNet-50, Wide-ResNet, LSTM,
  DCGAN/WGAN) conforming to a duck-typed model contract;
- a parallel **data layer** with compute/IO overlap (``para_load`` equivalent);
- a **launcher** (``tmlauncher`` equivalent), **recorder**, and checkpointing.

Nothing here is a port: there is no mpirun, no per-GPU process, no NCCL.  One
controller traces the training step once; XLA compiles it SPMD over a
``jax.sharding.Mesh`` and inserts ICI collectives where the shardings demand.
"""

__version__ = "0.1.0"

_RULES = {
    "BSP": "theanompi_tpu.parallel.bsp",
    "EASGD": "theanompi_tpu.parallel.easgd",
    "GOSGD": "theanompi_tpu.parallel.gosgd",
    # periodic parameter averaging — EASGD's diagnosis control and a rule
    # in its own right (k-step averaging)
    "LocalSGD": "theanompi_tpu.parallel.easgd",
}

__all__ = ["BSP", "EASGD", "GOSGD", "LocalSGD", "__version__"]


def __getattr__(name):
    # Lazy rule imports keep `import theanompi_tpu` cheap (no jax trace-time
    # imports until a rule is actually used), mirroring the reference's
    # top-level `from theanompi import BSP` API (SURVEY.md §2.1, unverified).
    if name in _RULES:
        import importlib

        try:
            return getattr(importlib.import_module(_RULES[name]), name)
        except ImportError as e:
            raise AttributeError(
                f"rule {name!r} failed to import from {_RULES[name]}: {e}"
            ) from e
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
