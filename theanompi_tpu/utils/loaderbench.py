"""Host input-pipeline benchmark vs chip demand (VERDICT r2 #7).

para_load existed to keep 2016 GPUs fed (SURVEY.md §3.5); the analogous
question here is whether the host can augment images as fast as the
measured train step consumes them (~2.5k img/s/chip on the v5e bench).
This tool measures, on real ``.npy`` shards written to a temp dir (so the
numbers reflect the disk+page-cache path, not the synthetic generator):

- the crop/mirror kernel alone: C (``theanompi_tpu.native``) vs the numpy
  reference loop;
- the full ``ImageNetData.train_batches`` pipeline (load + augment +
  shuffle + batch assembly) at worker counts 0 (inline) and N
  (the fork-pool loader);

and writes ``LOADER.json`` with an explicit ``feeds_chip`` verdict per
configuration.  CLI::

    python -m theanompi_tpu.utils.loaderbench --demand 2473 --out LOADER.json
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import numpy as np


def _rate(fn, min_seconds: float = 2.0) -> float:
    """imgs/sec of ``fn() -> n_images``, best of the timed window."""
    fn()  # warm (page cache, pool fork, native lib build)
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < min_seconds:
        n += fn()
    return n / (time.perf_counter() - t0)


def bench_crop(store: int = 256, out: int = 224, shard: int = 128) -> dict:
    from theanompi_tpu import native
    from theanompi_tpu.models.data.imagenet import random_crop_mirror

    x = np.random.randint(0, 255, (shard, store, store, 3), np.uint8)
    rng = np.random.RandomState(0)
    res = {}
    res["crop_c_imgs_per_sec"] = (
        round(_rate(lambda: len(random_crop_mirror(x, out, rng))), 1)
        if native.available() else None
    )
    orig = native.crop_mirror_batch
    native.crop_mirror_batch = lambda *a, **k: None
    try:
        res["crop_numpy_imgs_per_sec"] = round(
            _rate(lambda: len(random_crop_mirror(x, out, rng))), 1)
    finally:
        native.crop_mirror_batch = orig
    return res


def bench_pipeline(workers: int, n_images: int = 2048, store: int = 256,
                   out: int = 224, shard: int = 128,
                   batch_size: int = 256, tmpdir: str | None = None) -> float:
    from theanompi_tpu.models.data.imagenet import ImageNetData, write_shards

    d = tmpdir or tempfile.mkdtemp(prefix="loaderbench_")
    if not os.path.isdir(os.path.join(d, "train")):
        xs = np.random.randint(0, 255, (n_images, store, store, 3), np.uint8)
        ys = np.random.randint(0, 1000, n_images).astype(np.int32)
        write_shards(os.path.join(d, "train"), xs, ys, shard)
        write_shards(os.path.join(d, "val"), xs[:shard], ys[:shard], shard)
    data = ImageNetData({"data_path": d, "image_size": out,
                         "loader_workers": workers})
    epoch = [0]

    def one_epoch():
        n = 0
        for b in data.train_batches(batch_size, epoch[0]):
            n += len(b["x"])
        epoch[0] += 1
        return n

    try:
        return _rate(one_epoch, min_seconds=4.0)
    finally:
        data.cleanup()  # the persistent worker ring + its shm


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--demand", type=float, default=2473.0,
                   help="chip demand in img/s (BENCH_r02 ResNet-50)")
    p.add_argument("--workers", default="0,2,4,8")
    p.add_argument("--n-images", type=int, default=2048)
    p.add_argument("--out", default="LOADER.json")
    args = p.parse_args(argv)

    art = {"chip_demand_imgs_per_sec": args.demand, **bench_crop()}
    tmpdir = tempfile.mkdtemp(prefix="loaderbench_")
    art["pipeline"] = []
    for w in (int(x) for x in args.workers.split(",")):
        r = bench_pipeline(w, n_images=args.n_images, tmpdir=tmpdir)
        art["pipeline"].append({
            "workers": w,
            "imgs_per_sec": round(r, 1),
            "feeds_chip": bool(r >= args.demand),
        })
        print(json.dumps(art["pipeline"][-1]), flush=True)
    best = max(art["pipeline"], key=lambda r: r["imgs_per_sec"])
    art["verdict"] = (
        f"workers={best['workers']} sustains {best['imgs_per_sec']:.0f} "
        f"img/s vs demand {args.demand:.0f} "
        f"({'FEEDS' if best['feeds_chip'] else 'STARVES'} the chip)"
    )
    art["note"] = (
        "on an idle host the inline (workers=0) path already feeds the "
        "chip — the per-shard work is mostly the GIL-free C crop kernel, "
        "and the worker ring's parent-side assembly (slot memcpy + batch "
        "concat) caps its advantage; the ring's value is contended hosts "
        "(measured 1.0k img/s inline under a full test-suite run, i.e. "
        "starving) and costlier augmentations"
    )
    with open(args.out + ".tmp", "w") as f:
        json.dump(art, f, indent=1)
    os.replace(args.out + ".tmp", args.out)
    print(json.dumps({"verdict": art["verdict"], "out": args.out}))


if __name__ == "__main__":
    main()
