"""Convergence evidence for the conv zoo (VERDICT r2 #8).

The reference's validation discipline was convergence-as-test (SURVEY.md
§4: AlexNet top-1 against the paper's number); round 2 only ever took one
step of the ImageNet-class models in CI.  This harness trains, in bounded
minutes on the virtual mesh:

- **ResNet-50** (small-image head: 64 px, 10-class synthetic shards),
  **AlexNet with grouped convs**, and **VGG-11 (+BN)** to fixed
  validation-error targets under the BSP rule, reusing the rulecomp
  train-to-target machinery;
- **DCGAN** for a few epochs, then records a sample-quality proxy:
  per-pixel std across generated samples (mode-collapse detector — a
  collapsed generator emits near-identical images) and the discriminator's
  real-vs-fake score gap (a converging GAN keeps D near chance).

Writes ``CONVERGE.json`` with the full val-error curves, the proxy values,
and explicit pass/fail per model.  CLI::

    python -m theanompi_tpu.utils.converge --out CONVERGE.json \
        --force-host-devices 8
"""

from __future__ import annotations

import argparse
import json

import numpy as np

#: (name, modelfile, modelclass, config, target_error, max_epochs)
CLASSIFIER_RUNS = [
    (
        "resnet50_small",
        "theanompi_tpu.models.resnet50", "ResNet50",
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "lr": 0.02, "lr_decay_epochs": (), "weight_decay": 0.0,
         "precision": "fp32"},
        0.25, 12,
    ),
    (
        "alexnet_grouped",
        "theanompi_tpu.models.alex_net", "AlexNet",
        # lr: without BN the he_normal-init FC stack is unstable above
        # ~3e-3 at this scale (single-batch memorization probe: lr 0.01
        # plateaus at err 0.69, lr 0.001 memorizes to 0.00)
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "grouped": True, "dropout": 0.25, "lr": 0.002,
         "lr_decay_epochs": (), "weight_decay": 0.0, "precision": "fp32"},
        0.35, 25,
    ),
    (
        "vgg11",
        "theanompi_tpu.models.vggnet_16", "VGGNet_11_Shallow",
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "dropout": 0.25, "lr": 0.002, "bn": True,
         "lr_decay_epochs": (), "weight_decay": 0.0, "precision": "fp32"},
        0.35, 20,
    ),
]

#: models deliberately NOT in the bounded harness, with why (emitted into
#: the artifact so regeneration preserves the record)
EXCLUDED = {
    "googlenet_aux": (
        "learns but converges too slowly for the bounded-minutes gate at "
        "the 512-image/64px no-BN scale: probed best val error 0.64 after "
        "20 epochs at lr 2e-3 and 0.77 after 12 at lr 1e-3/5e-3; "
        "correctness is covered by the aux-head gradient-flow tests "
        "(tests/test_zoo.py), full convergence needs the real-data scale "
        "the reference used"
    ),
}


def converge_classifiers(devices=8, runs=None, verbose=True) -> list[dict]:
    from theanompi_tpu import BSP
    from theanompi_tpu.utils.rulecomp import run_to_target

    rows = []
    for name, mf, mc, cfg, target, max_epochs in (runs or CLASSIFIER_RUNS):
        rule = BSP(config={"seed": 0, "verbose": False})
        row = run_to_target(
            rule, devices=devices, model_config=dict(cfg),
            target_error=target, max_epochs=max_epochs,
            modelfile=mf, modelclass=mc,
        )
        row = {"model": name, "target_error": target,
               "passed": row["reached"], **row}
        rows.append(row)
        if verbose:
            print(json.dumps({k: row[k] for k in
                              ("model", "passed", "epochs_to_target",
                               "best_val_error")}), flush=True)
    return rows


def converge_dcgan(devices=8, n_epochs=30, verbose=True) -> dict:
    """Train DCGAN briefly; -> curves + sample-quality proxy row.

    Proxies (both cheap, both catch the classic failure modes):
    - ``sample_std``: mean per-pixel std across 64 generated samples in
      the tanh [-1, 1] range.  Mode collapse drives it toward 0; the
      synthetic CIFAR reals sit around ~0.3.
    - ``disc_gap``: |sigmoid(D(real)) - sigmoid(D(fake))| batch means — a
      discriminator that cleanly separates real from fake (gap -> 1)
      means the generator lost; training health keeps it moderate.
    """
    import jax
    import jax.numpy as jnp

    from theanompi_tpu.models.dcgan import DCGAN
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh
    from theanompi_tpu.utils.recorder import Recorder

    # disc_base < gen_base: at this tiny scale a matched discriminator
    # saturates (gap -> 0.96) before the generator learns; weakening D
    # keeps the game balanced (measured: gap 0.49 with std 0.08 at 30
    # epochs vs gap 0.96 matched)
    cfg = {"batch_size": 8, "image_size": 32, "gen_base": 64, "disc_base": 16,
           "z_dim": 32, "n_train": 256, "n_val": 64, "n_epochs": n_epochs,
           "precision": "fp32", "verbose": False}
    model = DCGAN(cfg)
    mesh = make_mesh(n_data=devices)
    # print_freq=8: train_history only fills at print boundaries (the
    # recorder never records per-iteration to avoid device syncs), so a
    # huge print_freq would leave the loss curves EMPTY
    trainer = BSPTrainer(model, mesh=mesh,
                         recorder=Recorder(verbose=False, print_freq=8))
    rec = trainer.run()

    params = trainer.params
    cast = model.precision.cast_to_compute
    z = jax.random.normal(jax.random.PRNGKey(7), (64, cfg["z_dim"]),
                          jnp.float32)
    fake, _ = model._sample(cast(params["gen"]), trainer.state["gen"], z,
                            train=False)
    fake = np.asarray(fake, np.float32)
    sample_std = float(np.mean(fake.std(axis=0)))

    real = next(iter(model.data.val_batches(64)))["x"].astype(np.float32)
    s_real, _ = model.disc.apply(cast(params["disc"]), trainer.state["disc"],
                                 jnp.asarray(real))
    s_fake, _ = model.disc.apply(cast(params["disc"]), trainer.state["disc"],
                                 jnp.asarray(fake))
    def sigmoid(a):
        return 1.0 / (1.0 + np.exp(-np.asarray(a, np.float32)))

    gap = float(abs(np.mean(sigmoid(s_real)) - np.mean(sigmoid(s_fake))))
    row = {
        "model": "dcgan",
        "epochs": n_epochs,
        "d_loss_curve": [round(float(v), 4)
                         for v in rec.train_history.get("d_loss", [])][-50:],
        "g_loss_curve": [round(float(v), 4)
                         for v in rec.train_history.get("g_loss", [])][-50:],
        "sample_std": round(sample_std, 4),
        "disc_gap": round(gap, 4),
        # pass: generator not collapsed AND discriminator not saturated
        "passed": bool(sample_std > 0.05 and gap < 0.95),
    }
    if verbose:
        print(json.dumps({k: row[k] for k in
                          ("model", "passed", "sample_std", "disc_gap")}),
              flush=True)
    return row


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--dcgan-epochs", type=int, default=30)
    p.add_argument("--out", default="CONVERGE.json")
    p.add_argument("--force-host-devices", type=int, default=None)
    args = p.parse_args(argv)
    if args.force_host_devices:
        from theanompi_tpu.parallel.mesh import force_host_devices

        force_host_devices(args.force_host_devices)
    rows = converge_classifiers(devices=args.devices)
    rows.append(converge_dcgan(devices=args.devices,
                               n_epochs=args.dcgan_epochs))
    art = {"devices": args.devices, "results": rows,
           "passed": all(r["passed"] for r in rows),
           "excluded": EXCLUDED}
    with open(args.out, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({"passed": art["passed"], "out": args.out}))


if __name__ == "__main__":
    main()
