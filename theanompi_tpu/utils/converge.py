"""Convergence evidence for the conv zoo (VERDICT r2 #8).

The reference's validation discipline was convergence-as-test (SURVEY.md
§4: AlexNet top-1 against the paper's number); round 2 only ever took one
step of the ImageNet-class models in CI.  This harness trains, in bounded
minutes on the virtual mesh:

- **ResNet-50** (small-image head: 64 px, 10-class synthetic shards),
  **AlexNet with grouped convs**, **VGG-11 (+BN)** and **GoogLeNet (+BN)**
  to fixed validation-error targets, reusing the rulecomp train-to-target
  machinery — under BSP, plus one row pairing **EASGD (τ=4) with
  ResNet-50** (the reference's benchmark config 4) at the settings the
  r4 diagnosis validated;
- **LSTM and Transformer LMs** to a fixed validation PERPLEXITY target on
  the synthetic PTB stand-in, with the stream's computable entropy floor
  recorded next to the target (VERDICT r3 #7);
- **DCGAN** with a capacity-MATCHED discriminator balanced by the
  two-timescale update rule, gated on real-relative sample diversity, the
  discriminator's real-vs-fake score gap, and a sliced-Wasserstein
  distribution statistic calibrated against a real split-half baseline
  (VERDICT r3 #9).

Writes ``CONVERGE.json`` with the full val-error curves, the proxy values,
and explicit pass/fail per model.  CLI::

    python -m theanompi_tpu.utils.converge --out CONVERGE.json \
        --force-host-devices 8
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

#: (name, modelfile, modelclass, config, target_error, max_epochs[,
#:  rule_class, rule_config]) — rule defaults to BSP; the EASGD row
#: exists because the reference's benchmark config 4 was specifically
#: EASGD + ResNet-50 (BASELINE.md), so rule-under-convergence parity
#: needs that pairing, at the settings the r4 diagnosis found sound
#: (unscaled lr, tau=4, paper alpha)
CLASSIFIER_RUNS = [
    (
        "resnet50_small",
        "theanompi_tpu.models.resnet50", "ResNet50",
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "lr": 0.02, "lr_decay_epochs": (), "weight_decay": 0.0,
         "precision": "fp32"},
        0.25, 12,
    ),
    (
        "alexnet_grouped",
        "theanompi_tpu.models.alex_net", "AlexNet",
        # lr: without BN the he_normal-init FC stack is unstable above
        # ~3e-3 at this scale (single-batch memorization probe: lr 0.01
        # plateaus at err 0.69, lr 0.001 memorizes to 0.00)
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "grouped": True, "dropout": 0.25, "lr": 0.002,
         "lr_decay_epochs": (), "weight_decay": 0.0, "precision": "fp32"},
        0.35, 25,
    ),
    (
        "vgg11",
        "theanompi_tpu.models.vggnet_16", "VGGNet_11_Shallow",
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "dropout": 0.25, "lr": 0.002, "bn": True,
         "lr_decay_epochs": (), "weight_decay": 0.0, "precision": "fp32"},
        0.35, 20,
    ),
    (
        # the BN knob (VERDICT r3 #6): plain GoogLeNet was excluded in r3
        # (best val err 0.64 after 20 epochs — no-BN trainability, not a
        # model bug); BN-GoogLeNet memorizes a batch in <40 steps where
        # no-BN plateaued at err 0.69, and converges inside the gate
        "googlenet_bn",
        "theanompi_tpu.models.googlenet", "GoogLeNet",
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "bn": True, "dropout": 0.2, "lr": 0.01,
         "lr_decay_epochs": (), "weight_decay": 0.0, "precision": "fp32"},
        0.35, 20,
    ),
    (
        # the trained-as-shipped configuration (VERDICT r4 #6): the
        # reference trained GoogLeNet WITH its aux classifiers (SURVEY.md
        # §2.1), so the bn-only row proves a different network than the
        # paper's; this row turns both knobs on (aux losses join the train
        # cost at the paper's 0.3 weight, googlenet.py:285)
        "googlenet_bn_aux",
        "theanompi_tpu.models.googlenet", "GoogLeNet",
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "bn": True, "aux": True, "dropout": 0.2, "lr": 0.01,
         "lr_decay_epochs": (), "weight_decay": 0.0, "precision": "fp32"},
        0.35, 20,
    ),
    (
        "resnet50_easgd_tau4",
        "theanompi_tpu.models.resnet50", "ResNet50",
        {"image_size": 64, "store_size": 72, "n_classes": 10,
         "batch_size": 16, "n_train": 512, "n_val": 128, "shard_size": 128,
         "lr": 0.02, "lr_decay_epochs": (), "weight_decay": 0.0,
         "precision": "fp32"},
        0.25, 14,
        "EASGD", {"tau": 4, "scale_lr": False},
    ),
]

#: models deliberately NOT in the bounded harness, with why (emitted into
#: the artifact so regeneration preserves the record)
EXCLUDED: dict[str, str] = {}

#: sequence models trained to a PERPLEXITY target on the synthetic PTB
#: stand-in (VERDICT r3 #7 — the reference trained its LSTM to PTB
#: perplexity; zero-egress image, so the bigram stream with a computable
#: entropy floor substitutes).  (name, modelfile, modelclass, config,
#: target_ppl, max_epochs).  Floor at vocab 64 is exp(H) = 13.3; targets
#: sit between floor and the unigram ~55, so reaching them requires
#: actually learning the transition structure.
SEQUENCE_RUNS = [
    (
        # lr 1.0 + momentum 0.9: probed to reach train ppl ~13 (the floor)
        # in ~600 steps; lr 1.0/no-momentum creeps (ppl 57 after 120)
        "lstm_ptb_synth",
        "theanompi_tpu.models.lstm", "LSTM",
        {"batch_size": 8, "n_train": 2048, "n_val": 256, "seq_len": 32,
         "vocab": 64, "hidden": 128, "embed_dim": 128, "n_layers": 1,
         "dropout": 0.0, "lr": 1.0, "momentum": 0.9,
         "lr_decay_epochs": (), "grad_clip": 5.0, "precision": "fp32"},
        16.0, 25,
    ),
    (
        "transformer_ptb_synth",
        "theanompi_tpu.models.transformer_lm", "TransformerLM",
        {"batch_size": 8, "n_train": 2048, "n_val": 256, "seq_len": 32,
         "vocab": 64, "dim": 128, "heads": 4, "n_layers": 2,
         "dropout": 0.0, "lr": 0.01, "momentum": 0.9,
         "lr_decay_epochs": (), "grad_clip": 1.0, "precision": "fp32",
         "attn_impl": "blockwise"},
        16.0, 15,
    ),
]


def _bigram_floor_ppl(vocab: int, seed: int = 0) -> float:
    """exp(entropy rate) of the synthetic bigram stream — the perplexity a
    perfect model of the data would reach."""
    from theanompi_tpu.models.data.base import SyntheticSequenceDataset

    syn = SyntheticSequenceDataset(vocab=vocab, seed=seed)
    p = syn._probs
    pi = np.ones(vocab) / vocab
    for _ in range(200):
        pi = pi @ p
    h = -(pi[:, None] * p * np.log(np.maximum(p, 1e-12))).sum()
    return float(np.exp(h))


def converge_sequence_models(devices=8, runs=None, verbose=True,
                             seeds=(0, 1, 2), overshoot=0.5) -> list[dict]:
    """LM rows, multi-seed with a margin-forcing stop (VERDICT r4 #4).

    The r4 transformer row passed by 0.008 perplexity — an artifact of
    stop-at-target: ``run_to_target`` halts the moment the metric crosses
    the gate, so the recorded best sits epsilon under it no matter how much
    budget remains.  Each run now trains toward ``target - overshoot``
    (same epoch budget), which forces the recorded best at least
    ``overshoot`` below the REAL gate when the model has the capacity —
    pass/fail and epochs_to_target are still judged against the real
    target from the curve.  Every row runs ``seeds`` times; the artifact
    carries per-seed summaries + ``pass_rate`` (curve kept for seed 0).
    """
    from theanompi_tpu import BSP
    from theanompi_tpu.utils.rulecomp import run_to_target

    rows = []
    for name, mf, mc, cfg, target, max_epochs in (runs or SEQUENCE_RUNS):
        per_seed = []
        first = None
        for s in seeds:
            rule = BSP(config={"seed": s, "verbose": False})
            r = run_to_target(
                rule, devices=devices, model_config=dict(cfg),
                target_error=target - overshoot, max_epochs=max_epochs,
                modelfile=mf, modelclass=mc, metric="perplexity",
            )
            curve = r["val_perplexity_curve"]
            hits = [i for i, v in enumerate(curve) if v <= target]
            best = r["best_val_perplexity"]
            per_seed.append({
                "seed": s,
                "passed": bool(hits),
                "epochs_to_target": hits[0] if hits else None,
                "best_val_perplexity": best,
                "margin": (round(target - best, 4)
                           if best is not None else None),
            })
            if first is None:
                first = r
        row = {"model": name, "target_perplexity": target,
               "stop_target_perplexity": target - overshoot,
               "entropy_floor_perplexity":
                   round(_bigram_floor_ppl(cfg["vocab"]), 2),
               "passed": all(p["passed"] for p in per_seed),
               **first}
        # run_to_target's reached/epochs/steps fields refer to the
        # overshoot stop — rename them so the row can't carry two fields
        # silently keyed to different targets; the row-level verdict and
        # epochs_to_target are against the real gate
        row["reached_stop_target"] = row.pop("reached")
        row["epochs_to_stop_target"] = row.pop("epochs_to_target")
        row["steps_to_stop_target"] = row.pop("steps_to_target")
        row["epochs_to_target"] = per_seed[0]["epochs_to_target"]
        row["seeds"] = per_seed
        row["pass_rate"] = round(
            sum(p["passed"] for p in per_seed) / len(per_seed), 3)
        rows.append(row)
        if verbose:
            print(json.dumps({
                "model": name, "passed": row["passed"],
                "pass_rate": row["pass_rate"],
                "margins": [p["margin"] for p in per_seed],
                "entropy_floor_perplexity":
                    row["entropy_floor_perplexity"]}), flush=True)
    return rows


def converge_classifiers(devices=8, runs=None, verbose=True) -> list[dict]:
    import theanompi_tpu as tm
    from theanompi_tpu.utils.rulecomp import run_to_target

    rows = []
    for entry in (runs or CLASSIFIER_RUNS):
        name, mf, mc, cfg, target, max_epochs = entry[:6]
        rule_cls_name = entry[6] if len(entry) > 6 else "BSP"
        rule_cfg = dict(entry[7]) if len(entry) > 7 else {}
        rule = getattr(tm, rule_cls_name)(
            config={**rule_cfg, "seed": 0, "verbose": False})
        row = run_to_target(
            rule, devices=devices, model_config=dict(cfg),
            target_error=target, max_epochs=max_epochs,
            modelfile=mf, modelclass=mc,
        )
        row = {"model": name, "rule": rule_cls_name,
               "rule_config": rule_cfg, "target_error": target,
               "passed": row["reached"], **row}
        rows.append(row)
        if verbose:
            print(json.dumps({k: row[k] for k in
                              ("model", "passed", "epochs_to_target",
                               "best_val_error")}), flush=True)
    return rows


def _sliced_wasserstein(a: np.ndarray, b: np.ndarray, n_proj: int = 64,
                        seed: int = 0) -> float:
    """1-sliced-Wasserstein distance between two equal-size sample sets:
    mean |sorted projections| gap over random unit directions.  A
    distribution-level statistic — sensitive to mode collapse and mean/
    scale drift at once, cheap enough for the bounded harness."""
    rng = np.random.RandomState(seed)
    a = a.reshape(len(a), -1).astype(np.float64)
    b = b.reshape(len(b), -1).astype(np.float64)
    proj = rng.randn(a.shape[1], n_proj)
    proj /= np.linalg.norm(proj, axis=0, keepdims=True)
    pa = np.sort(a @ proj, axis=0)
    pb = np.sort(b @ proj, axis=0)
    return float(np.mean(np.abs(pa - pb)))


def _gan_eval_stats(model, trainer, z_dim: int):
    """Shared GAN measurement block: -> the 7-tuple
    ``(scores_real, scores_fake, fake_std, real_std, std_ratio,
    swd_fake_real, swd_real_real)`` — the two leading entries are raw
    critic/disc score arrays (real first), the rest are scalars; no
    sample images are returned.

    Invariants both GAN rows rely on: the 64-sample fake set comes from a
    FIXED key (comparable across runs), and both SWD statistics use the
    SAME sample size (32 vs 32) — finite-sample SWD shrinks with n, so
    mismatched sizes would silently loosen the gate.
    """
    import jax
    import jax.numpy as jnp

    params = trainer.params
    cast = model.precision.cast_to_compute
    z = jax.random.normal(jax.random.PRNGKey(7), (64, z_dim), jnp.float32)
    fake, _ = model._sample(cast(params["gen"]), trainer.state["gen"], z,
                            train=False)
    fake = np.asarray(fake, np.float32)
    real = next(iter(model.data.val_batches(64)))["x"].astype(np.float32)
    s_real, _ = model.disc.apply(cast(params["disc"]), trainer.state["disc"],
                                 jnp.asarray(real))
    s_fake, _ = model.disc.apply(cast(params["disc"]), trainer.state["disc"],
                                 jnp.asarray(fake))
    sample_std = float(np.mean(fake.std(axis=0)))
    real_std = float(np.mean(real.std(axis=0)))
    std_ratio = sample_std / max(real_std, 1e-6)
    swd_fr = _sliced_wasserstein(fake[::2], real[::2])
    swd_rr = _sliced_wasserstein(real[::2], real[1::2])
    # lint: donated-escape-ok — eval-only judge outputs; nothing in the
    # convergence harness donates buffers, and the caller only reduces
    return (np.asarray(s_real, np.float32), np.asarray(s_fake, np.float32),
            sample_std, real_std, std_ratio, swd_fr, swd_rr)


def _gan_multi_seed_row(model_cls, cfg, devices, seeds, judge,
                        base_row) -> dict:
    """Shared multi-seed GAN scaffold (code-review r5: the DCGAN and WGAN
    rows differ only in model class, gap statistic, and pass predicate).

    Trains one run per seed, evaluates via ``_gan_eval_stats``, and gates
    each with ``judge(s_real, s_fake, std_ratio, swd_fr, swd_rr) ->
    (gap_key, gap_value, passed)``.  Curves and full stats are kept from
    the FIRST seed (bounded artifact size); the row carries per-seed
    summaries, ``pass_rate``, and all-seeds ``passed``.
    """
    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh
    from theanompi_tpu.utils.recorder import Recorder

    mesh = make_mesh(n_data=devices)
    row = None
    per_seed = []
    for s in seeds:
        model = model_cls(cfg)
        # print_freq=8: train_history only fills at print boundaries (the
        # recorder never records per-iteration to avoid device syncs), so
        # a huge print_freq would leave the loss curves EMPTY
        trainer = BSPTrainer(model, mesh=mesh, seed=s,
                             recorder=Recorder(verbose=False, print_freq=8))
        rec = trainer.run()
        (s_real, s_fake, sample_std, real_std, std_ratio,
         swd_fr, swd_rr) = _gan_eval_stats(model, trainer, cfg["z_dim"])
        gap_key, gap_val, passed = judge(s_real, s_fake, std_ratio,
                                         swd_fr, swd_rr)
        per_seed.append({"seed": s, "passed": passed,
                         "std_ratio": round(std_ratio, 4),
                         gap_key: round(gap_val, 4),
                         "swd_fake_real": round(swd_fr, 4),
                         "swd_real_real": round(swd_rr, 4)})
        if row is None:
            row = {
                **base_row(model),
                "d_loss_curve": [round(float(v), 4) for v in
                                 rec.train_history.get("d_loss", [])][-50:],
                "g_loss_curve": [round(float(v), 4) for v in
                                 rec.train_history.get("g_loss", [])][-50:],
                "sample_std": round(sample_std, 4),
                "real_std": round(real_std, 4),
                "std_ratio": round(std_ratio, 4),
                gap_key: round(gap_val, 4),
                "swd_fake_real": round(swd_fr, 4),
                "swd_real_real": round(swd_rr, 4),
            }
    row["seeds"] = per_seed
    row["pass_rate"] = round(sum(p["passed"] for p in per_seed)
                             / len(per_seed), 3)
    row["passed"] = all(p["passed"] for p in per_seed)
    return row


def converge_wgan(devices=8, n_epochs=20, verbose=True,
                  seeds=(0, 1, 2)) -> dict:
    """WGAN health row (reference config 5 lists BOTH GAN variants).

    WGAN's critic is trained toward the Wasserstein distance, so the
    natural health signals differ from DCGAN's: the critic's real-fake
    score gap IS the W-distance estimate (small and shrinking = G close;
    large = G lost), and weight clipping keeps scores bounded, so no
    sigmoid saturation gate applies.  Probed at this scale (matched
    64/64 nets, RMSProp/paper lr, n_critic=5, 20 epochs): std_ratio
    0.54, critic gap 0.05 — healthier than the DCGAN setting, no TTUR
    needed (the n_critic schedule is WGAN's own balancing mechanism).
    Gates: std_ratio > 0.33 (collapse), |critic_gap| < 1.0 (G lost —
    clipped-critic scores live in single digits), and the same
    split-half-calibrated sliced-Wasserstein gate as DCGAN.
    """
    from theanompi_tpu.models.dcgan import WGAN

    cfg = {"batch_size": 8, "image_size": 32, "gen_base": 64, "disc_base": 64,
           "z_dim": 32, "n_train": 256, "n_val": 64, "n_epochs": n_epochs,
           "precision": "fp32", "verbose": False}

    def judge(s_real, s_fake, std_ratio, swd_fr, swd_rr):
        critic_gap = float(np.mean(s_real) - np.mean(s_fake))
        return "critic_gap", critic_gap, bool(
            std_ratio > 0.33 and abs(critic_gap) < 1.0
            and swd_fr < 4.0 * swd_rr)

    row = _gan_multi_seed_row(
        WGAN, cfg, devices, seeds, judge,
        lambda model: {"model": "wgan_matched", "epochs": n_epochs,
                       "n_critic": model.config["n_critic"]})
    if verbose:
        print(json.dumps({k: row[k] for k in
                          ("model", "passed", "pass_rate", "std_ratio",
                           "critic_gap", "swd_fake_real", "swd_real_real")}),
              flush=True)
    return row


def converge_dcgan(devices=8, n_epochs=15, verbose=True,
                   seeds=(0, 1, 2)) -> dict:
    """Train DCGAN with a MATCHED discriminator; -> curves + proxy row.

    VERDICT r3 #9: the old evidence passed by under-building D
    (disc_base 16 vs gen_base 64).  The balanced setting is now capacity-
    matched (64/64) with the two-timescale update rule instead —
    ``disc_lr_scale 0.25`` (measured at this scale: a matched D at equal
    LRs saturates to gap 0.98 by epoch 30; at 0.25x it holds gap ~0.2
    while G learns).  Training stops at the measured balance window
    (proxies tracked over 90 epochs: std 0.086/gap 0.18 at ep 15 decaying
    to std 0.037/gap 0.71 by ep 75 — tiny-data GANs degrade past the
    window, so a bounded run is the honest setting).

    Proxies, all thresholds away from their failure bounds:
    - ``std_ratio`` = sample_std / real_std (real-relative, not absolute:
      collapse sits at ~0.24 here, healthy ~0.4; gate at 0.33);
    - ``disc_gap`` |sigmoid(D(real)) - sigmoid(D(fake))|: saturation = 1,
      gate at 0.8;
    - ``swd_fake_real`` vs the ``swd_real_real`` split-half baseline:
      a distribution-level statistic (sliced Wasserstein) comparing the
      generated set against the real set, calibrated by how far apart two
      real halves sit.
    """
    from theanompi_tpu.models.dcgan import DCGAN

    cfg = {"batch_size": 8, "image_size": 32, "gen_base": 64, "disc_base": 64,
           "z_dim": 32, "n_train": 256, "n_val": 64, "n_epochs": n_epochs,
           "disc_lr_scale": 0.25, "precision": "fp32", "verbose": False}

    def judge(s_real, s_fake, std_ratio, swd_fr, swd_rr):
        def sigmoid(a):
            return 1.0 / (1.0 + np.exp(-a))

        gap = float(abs(np.mean(sigmoid(s_real)) - np.mean(sigmoid(s_fake))))
        # pass: not collapsed (real-relative), D not saturated, and the
        # generated DISTRIBUTION within 4x the real split-half distance
        # (measured healthy run: 2.4x; collapse blows the sorted-projection
        # gaps up along with the std ratio)
        return "disc_gap", gap, bool(std_ratio > 0.33 and gap < 0.8
                                     and swd_fr < 4.0 * swd_rr)

    row = _gan_multi_seed_row(
        DCGAN, cfg, devices, seeds, judge,
        lambda model: {"model": "dcgan_matched", "epochs": n_epochs,
                       "gen_base": cfg["gen_base"],
                       "disc_base": cfg["disc_base"],
                       "disc_lr_scale": cfg["disc_lr_scale"]})
    if verbose:
        print(json.dumps({k: row[k] for k in
                          ("model", "passed", "pass_rate", "std_ratio",
                           "disc_gap", "swd_fake_real", "swd_real_real")}),
              flush=True)
    return row


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--dcgan-epochs", type=int, default=15)
    p.add_argument("--wgan-epochs", type=int, default=20)
    p.add_argument("--out", default="CONVERGE.json")
    p.add_argument("--force-host-devices", type=int, default=None)
    args = p.parse_args(argv)
    if args.force_host_devices:
        from theanompi_tpu.parallel.mesh import force_host_devices

        force_host_devices(args.force_host_devices)
    rows = converge_classifiers(devices=args.devices)
    rows += converge_sequence_models(devices=args.devices)
    rows.append(converge_dcgan(devices=args.devices,
                               n_epochs=args.dcgan_epochs))
    rows.append(converge_wgan(devices=args.devices,
                              n_epochs=args.wgan_epochs))
    art = {"devices": args.devices, "results": rows,
           "passed": all(r["passed"] for r in rows),
           "excluded": EXCLUDED,
           # scope notes: what a row does and does NOT establish
           "notes": {
               "googlenet_bn": (
                   "the bn=True, aux=False configuration; the as-shipped "
                   "trained configuration (aux classifiers ON, paper "
                   "weight 0.3) is the separate googlenet_bn_aux row "
                   "(VERDICT r4 #6)"
               ),
               "seeds": (
                   "LM and GAN rows run 3 seeds with per-seed summaries "
                   "and pass_rate; LM rows train toward target-0.5 (the "
                   "stop_target) so the recorded best carries visible "
                   "margin under the real gate instead of stopping "
                   "epsilon past it (VERDICT r4 #4)"
               ),
           }}
    with open(args.out + ".tmp", "w") as f:
        json.dump(art, f, indent=1)
    os.replace(args.out + ".tmp", args.out)
    print(json.dumps({"passed": art["passed"], "out": args.out}))


if __name__ == "__main__":
    main()
