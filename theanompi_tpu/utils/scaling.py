"""Scaling-efficiency measurement harness (the north-star metric's tool).

BASELINE.json's north star is >= 90% linear BSP scaling efficiency for
ResNet-50 on a TPU pod; the reference paper's headline was near-linear
AlexNet speedup to 8 GPUs (SURVEY.md §6, unverified).  This harness makes
that checkable: for each worker count n it measures pipelined step time on an
n-device data mesh and reports

- **weak-scaling efficiency**: images/sec/chip at n relative to n=1 (the
  per-worker batch is fixed, the global batch grows with n — the reference's
  setting);
- **comm share**: the fraction of device op time spent in collectives,
  measured from the profiler trace (``measure_comm_share`` — per-op device
  events, collective kinds summed; validated by an injection test that
  plants a fat collective and asserts a nonzero share).  The old
  *differential* estimate (same step compiled with the ``none`` strategy)
  is kept as ``comm_share_differential`` for comparison, but it is
  noise-dominated on shared/virtual setups and never resolved a signal.

Run on the CPU fake mesh (collectives are memcpys — the harness validates
the *machinery* and gives an upper bound on framework overhead) or on a real
multi-chip slice (the numbers that count).  CLI::

    python -m theanompi_tpu.utils.scaling --ns 1,2,4,8 --out SCALING.json
    # no multi-chip hardware? add --virtual 8 (forces host devices)
    # exchange-strategy microbenchmark (HLO collective counts + static
    # wire bytes per strategy — exact on any backend):
    python -m theanompi_tpu.utils.scaling --exchange-bench --ns 4 \
        --strategies psum,psum_bucket,ring_int8,zero1 --out EXCHANGE.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import tempfile

import numpy as np

#: collective names across both backends: TPU HLO instruction kinds (via
#: the roofline op classifier) and CPU thunk/primitive names
_CPU_COLLECTIVES = ("psum", "pmean", "all_gather", "all_to_all", "ppermute",
                    "reduce_scatter", "all-reduce", "all-gather",
                    "all-to-all", "collective-permute", "reduce-scatter")
_CPU_OP_RE = re.compile(r"^[a-z][\w\-]*(\.\d+)?$")


def _trace_comm_split(logdir: str) -> tuple[float, float]:
    """-> (collective seconds, total op seconds) from the newest xplane.

    TPU: the device plane's per-HLO-op events (same classification as the
    roofline tool).  CPU (virtual mesh): the ``tf_XLA*`` executor lines
    carry per-thunk events named after the lowered primitives
    (``psum.7``, ``dot_general.3``); summing across worker threads
    weights ops by total worker time, which is the right denominator for
    a SHARE (the absolute seconds are thread-summed, not wall — see
    ``comm_op_s_per_step``).  Validated by an injection test that plants
    a deliberately fat collective and asserts a nonzero share (VERDICT
    r2 #5 — the old differential method never measured anything but 0).
    The xplane is parsed exactly once and both backends read the same
    ``XSpace``.
    """
    import glob
    import os

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    from theanompi_tpu.utils.roofline import _op_kind

    paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {logdir}")
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())

    comm = total = 0.0
    saw_device = False
    for plane in xs.planes:
        if "TPU" not in plane.name and "device" not in plane.name.lower():
            continue
        emeta = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                saw_device = True
                kind = _op_kind(emeta.get(ev.metadata_id, ""))
                if kind == "while":
                    continue
                total += ev.duration_ps
                if kind == "collective":
                    comm += ev.duration_ps
    if saw_device:
        return comm / 1e12, total / 1e12

    # CPU fallback: executor thread lines on the host plane
    for plane in xs.planes:
        if "CPU" not in plane.name:
            continue
        emeta = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            if not line.name.startswith("tf_XLA"):
                continue
            for ev in line.events:
                nm = emeta.get(ev.metadata_id, "")
                if not _CPU_OP_RE.match(nm):
                    continue  # waits, rendezvous, pool bookkeeping, end: markers
                total += ev.duration_ps
                base = nm.split(".")[0]
                if base in _CPU_COLLECTIVES:
                    comm += ev.duration_ps
    return comm / 1e12, total / 1e12


def _have_xplane_protos() -> bool:
    """Whether tensorflow's xplane protos (the trace parser's only
    third-party need) are importable — probed before any profiled run."""
    import importlib.util

    try:
        return importlib.util.find_spec(
            "tensorflow.tsl.profiler.protobuf.xplane_pb2") is not None
    except Exception:  # lint: swallow-ok — degrade to null comm_share
        # the intent is "null comm_share instead of crashing" on ANY broken
        # tensorflow install — find_spec can raise more than ImportError
        # (e.g. a protobuf version mismatch during package init, ADVICE r4)
        return False


def measure_comm_share(trainer, batches, steps: int = 6, lr: float = 0.01):
    """Profiler-backed communication share of the train step.

    -> (comm_share, comm_seconds, total_op_seconds).  Runs ``steps``
    dispatched steps under ``jax.profiler.trace`` (single end sync, the
    bench dispatch pattern) and splits device-side op time into
    collective vs everything else.
    """
    import jax

    m = trainer.train_iter(batches[0], lr=lr)  # warm outside the trace
    float(m["cost"])
    with tempfile.TemporaryDirectory(prefix="commshare_") as logdir:
        with jax.profiler.trace(logdir):
            for i in range(steps):
                m = trainer.train_iter(batches[i % len(batches)], lr=lr)
            float(m["cost"])
        comm_s, total_s = _trace_comm_split(logdir)
    return (comm_s / total_s if total_s else 0.0), comm_s, total_s


def _build(model_name: str, model_config: dict, n: int, strategy: str,
           bucket_mb: float = 4.0, overlap: bool = False,
           telemetry_dir: str | None = None):
    import jax

    from theanompi_tpu.parallel.bsp import BSPTrainer
    from theanompi_tpu.parallel.mesh import make_mesh
    from theanompi_tpu.utils.helper_funcs import import_model, shard_batch
    from theanompi_tpu.utils.recorder import Recorder

    model_cls = import_model(f"theanompi_tpu.models.{model_name}",
                             {"wide_resnet": "WideResNet",
                              "resnet50": "ResNet50",
                              "alex_net": "AlexNet"}.get(model_name, model_name))
    cfg = dict(model_config)
    if n > 1:
        cfg.setdefault("bn_axis", "data")  # BSP default: sync-BN
    model = model_cls(cfg)
    mesh = make_mesh(n_data=n, devices=jax.devices()[:n])
    telemetry = None
    if telemetry_dir:
        # ISSUE 13: an opted-in bench rung is health-watchable live
        # (tmhealth <dir>) — per-step spans add host overhead, so the
        # measured numbers are only comparable to other telemetry-on runs
        from theanompi_tpu.telemetry import Telemetry

        telemetry = Telemetry(telemetry_dir, health=True,
                              flight_recorder=256)
    trainer = BSPTrainer(model, mesh=mesh, exch_strategy=strategy,
                         exch_bucket_mb=bucket_mb, exch_overlap=overlap,
                         telemetry=telemetry,
                         recorder=Recorder(verbose=False, print_freq=10**9))
    trainer.compile_iter_fns()
    trainer.init_state()
    batches = [
        shard_batch(mesh, b, spec=trainer.batch_spec)
        for b in model.data.train_batches(trainer.global_batch, 0, seed=0)
    ]
    jax.block_until_ready(batches)
    return trainer, batches


def measure_scaling(
    model_name: str = "wide_resnet",
    model_config: dict | None = None,
    ns=(1, 2, 4, 8),
    steps: int = 10,
    trials: int = 3,
    strategy: str = "psum",
    out_path: str | None = None,
    telemetry_dir: str | None = None,
) -> dict:
    """-> the artifact dict (and writes it to ``out_path`` if given)."""
    import jax

    from theanompi_tpu.utils.benchlib import best_trial

    model_config = model_config or {
        "batch_size": 32, "n_train": 256, "n_val": 64,
        "n_epochs": 1, "augment": False, "verbose": False,
    }
    per_n = {}
    # probed ONCE before the loop (ADVICE r4: calling it per n re-imported
    # tensorflow every iteration) — and only when some rung will measure
    # comm share at all (the n=1 rung has no collectives to profile)
    have_xplane = any(n > 1 for n in ns) and _have_xplane_protos()
    for n in ns:
        # per-rung telemetry subdir: each rung's sink would otherwise
        # truncate the previous rung's events
        tdir = (None if telemetry_dir is None
                else f"{telemetry_dir}/n{int(n)}")
        trainer, batches = _build(model_name, model_config, n, strategy,
                                  telemetry_dir=tdir)
        # warmup: compile both programs' first dispatch
        m = trainer.train_iter(batches[0], lr=0.01)
        float(m["cost"])
        (dt, _, _), results = best_trial(trainer, batches, steps, trials)
        times = [r[0] for r in results]
        if trainer.telemetry is not None:
            trainer.telemetry.close()

        t_noex = dt
        comm_share = comm_s = 0.0
        if n > 1:
            tr2, b2 = _build(model_name, model_config, n, "none")
            m = tr2.train_iter(b2[0], lr=0.01)
            float(m["cost"])
            (t_noex, _, _), _ = best_trial(tr2, b2, steps, trials)
            # profiler-backed split (the validated measurement; the
            # differential column is kept for comparison but is
            # noise-dominated on shared/virtual setups).  The xplane
            # parser needs tensorflow's profiler protos — on a JAX-only
            # install record comm_share as null instead of crashing
            # (ADVICE r3 #1); the differential column below remains the
            # only estimate in that case.  Availability was probed once
            # before the loop so no profiled run is wasted.
            if have_xplane:
                comm_share, comm_s, _ = measure_comm_share(
                    trainer, batches, steps=steps)
            else:
                comm_share = comm_s = None

        ips = steps * trainer.global_batch / dt
        per_n[int(n)] = {
            "global_batch": trainer.global_batch,
            "step_ms": round(dt / steps * 1e3, 3),
            "imgs_per_sec": round(ips, 2),
            "imgs_per_sec_per_chip": round(ips / n, 2),
            "comm_share": (None if comm_share is None
                           else round(comm_share, 4)),
            # thread-summed op seconds (NOT wall time — on an n-device
            # virtual mesh the executor threads' durations add up): only
            # meaningful relative to the same sum for all ops, which is
            # exactly what comm_share reports
            "comm_op_s_per_step": (None if comm_s is None
                                   else round(comm_s / steps, 6)),
            "comm_share_differential": (
                round(max(0.0, 1.0 - t_noex / dt), 4) if n > 1 else 0.0),
            "trial_s": [round(t, 4) for t in times],
        }
    for n in ns:
        per_n[int(n)]["efficiency"] = round(
            per_n[int(n)]["imgs_per_sec_per_chip"]
            / per_n[int(ns[0])]["imgs_per_sec_per_chip"],
            4,
        )
    artifact = {
        "model": model_name,
        "strategy": strategy,
        "platform": jax.devices()[0].platform,
        "steps": steps,
        "trials": trials,
        "ns": [int(n) for n in ns],
        # efficiency is relative to the SMALLEST measured n; only a run
        # whose ns include 1 measures the true vs-one-chip north star
        "efficiency_base_n": int(ns[0]),
        "per_n": per_n,
        "north_star": "efficiency >= 0.9 at pod scale (BASELINE.json)",
    }
    if jax.devices()[0].platform != "tpu":
        artifact["caveat"] = (
            "virtual host-device mesh: the n workers compete for the same "
            "host cores, so 'efficiency' here measures host-FLOP contention "
            "plus framework overhead, NOT interconnect scaling; only the "
            "machinery (mesh build, collectives, comm-share accounting) is "
            "being validated. The north-star number requires a real "
            "multi-chip slice."
        )
    if out_path:
        with open(out_path + ".tmp", "w") as f:
            json.dump(artifact, f, indent=1)
        os.replace(out_path + ".tmp", out_path)
    return artifact


#: the exchange microbenchmark's default strategy sweep
EXCHANGE_BENCH_STRATEGIES = (
    "psum", "psum_bf16", "psum_bucket", "psum_bf16_bucket",
    "ring", "ring_bucket", "ring_int8", "zero1",
)


def exchange_microbench(
    model_name: str = "wide_resnet",
    model_config: dict | None = None,
    n: int = 4,
    strategies=EXCHANGE_BENCH_STRATEGIES,
    steps: int = 4,
    trials: int = 1,
    bucket_mb: float = 4.0,
    overlap: bool = False,
    out_path: str | None = None,
) -> dict:
    """Exchange-strategy microbenchmark on an ``n``-device mesh.

    For each strategy: HLO-derived collective counts of the compiled train
    step (``telemetry.metrics.hlo_collective_counts`` — the honest
    launch-overhead proxy when the collective is fused into one XLA
    program), static per-step wire bytes (``Exchanger.wire_bytes``), bucket
    layout, and pipelined step time.  On the CPU fake mesh the *times*
    only bound framework overhead; the collective counts and byte
    accounting are exact on any backend — that is the point: bucketing
    regressions show up as op-count jumps with no TPU attached.

    ``overlap=True`` (ISSUE 12) adds the fused-vs-overlapped comparison:
    every bucketed strategy is built a second time with ``exch_overlap``
    and a shared ``none``-strategy baseline is measured once, so each row
    gains ``step_ms_overlap`` plus the differential comm shares
    ``comm_share_differential`` (fused) and
    ``comm_share_differential_overlap`` — the overlap claim is precisely
    that the second number approaches zero (comm hidden under backward)
    while wire bytes and collective counts stay identical.
    """
    import jax

    from theanompi_tpu.parallel.exchanger import BUCKETED_STRATEGIES
    from theanompi_tpu.telemetry.metrics import hlo_collective_counts
    from theanompi_tpu.utils.benchlib import best_trial

    model_config = model_config or {
        "batch_size": 8, "n_train": 64, "n_val": 16,
        "n_epochs": 1, "augment": False, "verbose": False,
    }

    def timed(strategy, ov=False):
        trainer, batches = _build(model_name, model_config, n, strategy,
                                  bucket_mb=bucket_mb, overlap=ov)
        m = trainer.train_iter(batches[0], lr=0.01)  # compile + warm
        float(m["cost"])
        counts = hlo_collective_counts(trainer.compiled_step_text(batches[0]))
        (dt, _, _), _ = best_trial(trainer, batches, steps, trials)
        return trainer, counts, dt

    t_base = None
    if overlap and n > 1:
        # ONE exchange-free baseline shared by every differential column
        _, _, t_base = timed("none")

    per_strategy = {}
    for strategy in strategies:
        trainer, counts, dt = timed(strategy)
        row = {
            "collectives": counts,
            "collective_ops_total": sum(counts.values()),
            "wire_bytes_per_step": trainer.exchange_wire_bytes(),
            "step_ms": round(dt / steps * 1e3, 3),
        }
        buckets = trainer.exchanger.bucket_summary(
            trainer._shard_param_structs(), n)
        if buckets:
            row["buckets"] = buckets
        if t_base is not None:
            row["comm_share_differential"] = round(
                max(0.0, 1.0 - t_base / dt), 4)
        if overlap and strategy in BUCKETED_STRATEGIES:
            _, counts_ov, dt_ov = timed(strategy, ov=True)
            row["step_ms_overlap"] = round(dt_ov / steps * 1e3, 3)
            # the schedule lock rides along: overlap must not change WHAT
            # is communicated, only WHEN (audited in analysis/hlo_audit)
            row["overlap_collectives_equal"] = (counts_ov == counts)
            if t_base is not None:
                row["comm_share_differential_overlap"] = round(
                    max(0.0, 1.0 - t_base / dt_ov), 4)
        per_strategy[strategy] = row
    artifact = {
        "model": model_name,
        "n": int(n),
        "platform": jax.devices()[0].platform,
        "steps": steps,
        "bucket_mb": bucket_mb,
        "overlap": bool(overlap),
        "per_strategy": per_strategy,
        "note": ("collective counts + wire bytes are static/exact on any "
                 "backend; step_ms is only meaningful on real chips"),
    }
    if out_path:
        with open(out_path + ".tmp", "w") as f:
            json.dump(artifact, f, indent=1)
        os.replace(out_path + ".tmp", out_path)
    return artifact


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="wide_resnet")
    p.add_argument("--ns", default="1,2,4,8")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--strategy", default="psum")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--set", dest="model_set", action="append", default=[],
                   metavar="K=V",
                   help="extra model-config entry (repeatable; same syntax "
                   "as tmlauncher --set, e.g. --set image_size=64)")
    p.add_argument("--out", default="SCALING.json")
    p.add_argument("--telemetry-dir", default=None,
                   help="per-rung telemetry + live health under "
                   "<dir>/n<N> (ISSUE 13; watch with tmhealth) — adds "
                   "per-step span overhead, so compare only against "
                   "other telemetry-on runs")
    p.add_argument("--virtual", type=int, default=0,
                   help="force N virtual host (CPU) devices first")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compilation-cache directory (same "
                   "knob as tmlauncher): a scaling sweep compiles one "
                   "program per rung, and re-runs/later rungs sharing the "
                   "dir skip recompiles")
    p.add_argument("--exchange-bench", action="store_true",
                   help="run the exchange-strategy microbenchmark instead "
                   "of the scaling ladder (HLO collective counts + static "
                   "wire bytes + step time per strategy)")
    p.add_argument("--strategies",
                   default=",".join(EXCHANGE_BENCH_STRATEGIES),
                   help="comma list for --exchange-bench")
    p.add_argument("--bucket-mb", type=float, default=4.0,
                   help="fused-bucket size for the bucketed strategies")
    p.add_argument("--overlap", action="store_true",
                   help="with --exchange-bench: add the fused-vs-overlapped "
                   "(exch_overlap) comparison column per bucketed strategy, "
                   "plus differential comm shares against a shared "
                   "no-exchange baseline")
    args = p.parse_args(argv)
    if args.virtual:
        from theanompi_tpu.parallel.mesh import force_host_devices

        force_host_devices(args.virtual)
    if args.compile_cache_dir:
        from theanompi_tpu.parallel.mesh import setup_compile_cache

        setup_compile_cache(args.compile_cache_dir)
    ns = tuple(int(x) for x in args.ns.split(","))
    cfg = {"batch_size": args.batch_size, "n_train": max(256, args.batch_size * 8),
           "n_val": 64, "n_epochs": 1, "augment": False, "verbose": False}
    from theanompi_tpu.launcher import _parse_kv

    cfg.update(_parse_kv(args.model_set))
    if args.exchange_bench:
        out = ("EXCHANGE.json" if args.out == "SCALING.json" else args.out)
        art = exchange_microbench(
            args.model, cfg, n=ns[-1],
            strategies=tuple(args.strategies.split(",")),
            steps=args.steps, trials=args.trials,
            bucket_mb=args.bucket_mb, overlap=args.overlap, out_path=out)
        for s, r in art["per_strategy"].items():
            c = r["collectives"]
            ov = (f"  ov {r['step_ms_overlap']:8.3f} ms"
                  if "step_ms_overlap" in r else "")
            print(f"{s:18s} step {r['step_ms']:8.3f} ms{ov}  "
                  f"wire {r['wire_bytes_per_step']:>12}  "
                  f"ar {c.get('all-reduce', 0):3d}  "
                  f"rs {c.get('reduce-scatter', 0):3d}  "
                  f"ag {c.get('all-gather', 0):3d}  "
                  f"perm {c.get('collective-permute', 0):3d}")
        print(f"wrote {out}")
        return
    art = measure_scaling(args.model, cfg, ns=ns, steps=args.steps,
                          trials=args.trials, strategy=args.strategy,
                          out_path=args.out,
                          telemetry_dir=args.telemetry_dir)
    for n in art["ns"]:
        r = art["per_n"][n]
        comm = ("  n/a" if r["comm_share"] is None
                else f"{r['comm_share']:5.3f}")
        print(f"n={n}: {r['imgs_per_sec']:9.1f} img/s "
              f"({r['imgs_per_sec_per_chip']:8.1f}/chip)  "
              f"eff {r['efficiency']:5.3f}  comm {comm}")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
