"""Per-op roofline profiling from the TPU's own trace (VERDICT r2 #1).

BASELINE.md claims the ResNet-50 step is HBM-bound; until round 3 that was
asserted from aggregate cost analysis, not shown.  This tool produces the
evidence: it runs the compiled train step under ``jax.profiler.trace``,
parses the xplane protobuf the TPU runtime writes (per-HLO-op device
durations, with the op's full HLO text embedded in the event name), and
joins three sources per op:

- **time**: device duration summed over the profiled steps (ground truth);
- **bytes**: operand + result tensor sizes parsed from the op's HLO text —
  an HBM-traffic estimate (exact for fusions, whose top-level operands and
  results are precisely what crosses HBM; VMEM-resident reuse inside a
  fusion never appears, which is the point);
- **flops**: ``dot``/``convolution`` instructions counted from the compiled
  module's text, including those INSIDE fused computations (attributed to
  the calling fusion op — the event text alone hides them).

Each op then gets achieved GB/s and TFLOP/s against the chip's peaks and a
verdict: ``hbm`` (>= 50% of peak bandwidth), ``mxu`` (>= 50% of peak
compute), or ``latency/other``.  The summary answers the roofline question
directly: what fraction of step time sits on ops already near a roof.

Collective ops (``all-reduce``/``all-gather``/``collective-permute``/
``all-to-all``) are tagged so the same trace yields the communication share
— the profiler-backed comm measurement VERDICT r2 #5 asks for (the old
differential method is noise-dominated on the virtual mesh).

CLI::

    python -m theanompi_tpu.utils.roofline --model resnet50 --out ROOFLINE.json
"""

from __future__ import annotations

import glob
import json
import os
import re
import tempfile
from collections import defaultdict

import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_SHAPE_RE = re.compile(r"\b(pred|bf16|f16|f32|f64|s8|u8|s16|u16|s32|u32|s64|u64)\[([0-9,]*)\]")

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")


def _numel(dims: str) -> int:
    if not dims:
        return 1
    return int(np.prod([int(d) for d in dims.split(",")]))


def _text_bytes(text: str) -> int:
    """Sum of all tensor-literal sizes in an HLO snippet (result+operands)."""
    return sum(_DTYPE_BYTES[m.group(1)] * _numel(m.group(2))
               for m in _SHAPE_RE.finditer(text))


def _split_top_level(s: str) -> list[str]:
    """Split on commas outside ``[]``/``{}`` (shape dims contain commas)."""
    out, cur, depth = [], [], 0
    for ch in s:
        if ch in "[{":
            depth += 1
        elif ch in "]}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _operand_names(line: str) -> list[str]:
    """Operand instruction names from the op's first paren group.

    Handles both HLO operand spellings: bare names (``dot(%a, %b)``) and
    typed operands (``dot(f32[8,4]{1,0} %a, ...)``, jax <= 0.4.x) — the
    instruction name is always the last whitespace-separated token of each
    top-level comma-separated operand.
    """
    m = re.search(r"\b(?:dot|convolution)\(([^)]*)\)", line)
    if not m:
        return []
    return [t.strip().split()[-1].lstrip("%")
            for t in _split_top_level(m.group(1)) if t.strip()]


def _dot_flops(line: str, shapes: dict[str, str]) -> int:
    """2*M*N*K*batch for an HLO ``dot``; operand shapes via symbol table."""
    ops = _operand_names(line)
    if len(ops) < 2:
        return 0
    lhs_s, rhs_s = shapes.get(ops[0]), shapes.get(ops[1])
    if lhs_s is None or rhs_s is None:
        return 0
    lhs_dims = [int(d) for d in lhs_s.split(",")] if lhs_s else []
    con = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", line)
    bat = re.search(r"lhs_batch_dims=\{([0-9,]*)\}", line)
    con_idx = [int(i) for i in con.group(1).split(",")] if con and con.group(1) else []
    bat_idx = [int(i) for i in bat.group(1).split(",")] if bat and bat.group(1) else []
    k = int(np.prod([lhs_dims[i] for i in con_idx])) if con_idx else 1
    b = int(np.prod([lhs_dims[i] for i in bat_idx])) if bat_idx else 1
    m = _numel(lhs_s) // max(k * b, 1)
    n = _numel(rhs_s) // max(k * b, 1)
    return 2 * b * m * n * k


def _win_field(line: str, key: str, ndim: int, default: int):
    m = re.search(rf"\b{key}=([0-9x_\-]+)", line)
    if not m:
        return [(default, default)] * ndim if key == "pad" else [default] * ndim
    parts = m.group(1).split("x")
    if key == "pad":
        # pad entries are "lo_hi"; a bare "N" means symmetric N
        return [tuple(int(v) for v in p.split("_")) if "_" in p
                else (int(p), int(p)) for p in parts]
    return [int(p) for p in parts]


def _conv_flops(line: str, shapes: dict[str, str]) -> int:
    """Exact 2*MACs for an HLO ``convolution``, any form (fwd/dgrad/wgrad).

    MACs are separable per spatial dim: for each output position, count the
    window taps that land inside the (lhs-dilated) input on real (non-hole)
    elements; the total is the product of per-dim sums times batch and the
    feature dims.  Grad convs' huge padded/dilated windows therefore count
    their TRUE work (a naive out*window*feat product over-counts them by
    the stride^2-and-more factors the zeros absorb).  The kernel ``i`` dim
    is per-group in HLO, so grouped convs need no extra division.
    """
    out = _SHAPE_RE.search(line)
    dl = re.search(r"dim_labels=(\w+)_(\w+)->(\w+)", line)
    win = re.search(r"window=\{size=([0-9x]+)", line)
    ops = _operand_names(line)
    if not (out and dl and len(ops) >= 2):
        return 0
    lhs_s, rhs_s = shapes.get(ops[0]), shapes.get(ops[1])
    if lhs_s is None or rhs_s is None:
        return 0
    lhs_spec, rhs_spec, out_spec = dl.groups()
    lhs_dims = [int(d) for d in lhs_s.split(",")]
    rhs_dims = [int(d) for d in rhs_s.split(",")]
    out_dims = [int(d) for d in out.group(2).split(",")]
    # matmuls lowered to HLO convolution carry NO window (dim_labels like
    # bf_io->bf): zero spatial dims, taps product stays 1
    sizes = [int(x) for x in win.group(1).split("x")] if win else []
    nd = len(sizes)
    strides = _win_field(line, "stride", nd, 1)
    pads = _win_field(line, "pad", nd, 0)
    lhs_dil = _win_field(line, "lhs_dilate", nd, 1)
    rhs_dil = _win_field(line, "rhs_dilate", nd, 1)
    taps_total = 1
    for d in range(nd):
        lab = str(d)
        in_sp = lhs_dims[lhs_spec.index(lab)]
        out_sp = out_dims[out_spec.index(lab)]
        k, st, (plo, _), ld, rd = sizes[d], strides[d], pads[d], lhs_dil[d], rhs_dil[d]
        in_eff = (in_sp - 1) * ld + 1
        base = np.arange(out_sp)[:, None] * st - plo
        ks = base + np.arange(k)[None, :] * rd
        valid = (ks >= 0) & (ks < in_eff) & (ks % ld == 0)
        taps_total *= int(valid.sum())
    b = lhs_dims[lhs_spec.index("b")]
    i = rhs_dims[rhs_spec.index("i")]
    of = out_dims[out_spec.index("f")]
    return 2 * b * i * of * taps_total


def hlo_flops_map(hlo_text: str) -> dict[str, int]:
    """instr-name -> flops for dots/convs, fused ones attributed to their
    calling fusion instruction."""
    lines = hlo_text.splitlines()
    # pass 1: symbol table (instruction name -> result shape dims string)
    shapes: dict[str, str] = {}
    defn = re.compile(r"(?:ROOT\s+)?%?([\w.\-]+)\s*=")
    for line in lines:
        im = defn.match(line.strip())
        if not im:
            continue
        sm = _SHAPE_RE.search(line)
        if sm:
            shapes.setdefault(im.group(1), sm.group(2))
    # pass 2: flops per dot/conv, attributed through fused computations
    comp_flops: dict[str, int] = defaultdict(int)
    flops: dict[str, int] = defaultdict(int)
    cur_comp = None
    fusion_calls: list[tuple[str, str]] = []
    for line in lines:
        ls = line.strip()
        if ls.endswith("{") and "(" in ls and "=" not in ls.split("(")[0]:
            cur_comp = ls.split()[0].lstrip("%").split("(")[0]
            continue
        if ls == "}":
            cur_comp = None
            continue
        im = defn.match(ls)
        if not im:
            continue
        name = im.group(1)
        f = 0
        if " dot(" in ls:
            f = _dot_flops(ls, shapes)
        elif " convolution(" in ls:
            f = _conv_flops(ls, shapes)
        if f:
            if cur_comp and cur_comp != "ENTRY":
                comp_flops[cur_comp] += f
            flops[name] += f
        cm = re.search(r"calls=%?([\w.\-]+)", ls)
        if cm and " fusion(" in ls:
            fusion_calls.append((name, cm.group(1)))
    for instr, comp in fusion_calls:
        if comp in comp_flops:
            flops[instr] += comp_flops[comp]
    return dict(flops)


def _load_xplane_ops(logdir: str):
    """-> list of (op_name, hlo_text, duration_ps) from the newest xplane."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {logdir}")
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    out = []
    for plane in xs.planes:
        if "TPU" not in plane.name and "device" not in plane.name.lower():
            continue
        emeta = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                text = emeta.get(ev.metadata_id, "")
                nm = text.split(" = ")[0].strip().lstrip("%") if " = " in text else text
                out.append((nm, text, int(ev.duration_ps)))
    return out


def _op_kind(text: str) -> str:
    for c in COLLECTIVE_KINDS:
        if f" {c}(" in text or f" {c}-start(" in text:
            return "collective"
    if " convolution(" in text:
        return "conv"
    if " dot(" in text:
        return "dot"
    if " fusion(" in text:
        if "convolution_fusion" in text or "conv" in text.split(" = ")[0]:
            return "conv-fusion"
        return "fusion"
    if " copy(" in text:
        return "copy"
    if " custom-call(" in text:
        return "custom-call"
    if " while(" in text:
        return "while"
    return "other"


def profile_step(trainer, batch, steps: int = 4, lr: float = 0.01,
                 peak_flops: float | None = None,
                 peak_gbps: float | None = None,
                 logdir: str | None = None) -> dict:
    """Profile ``steps`` dispatched train steps; -> the roofline artifact.

    The step must already be compiled+warmed (first call outside the trace).
    Ops are aggregated by name across steps and normalized per step.
    """
    import jax

    logdir = logdir or tempfile.mkdtemp(prefix="roofline_")
    m = trainer.train_iter(batch, lr=lr)   # warm outside the trace
    float(m["cost"])
    with jax.profiler.trace(logdir):
        for _ in range(steps):
            m = trainer.train_iter(batch, lr=lr)
        float(m["cost"])  # single sync, run()-loop dispatch pattern

    try:
        hlo = trainer.compiled_step_text(batch)
    except Exception:  # lint: swallow-ok — FLOP map degrades to empty
        hlo = ""
    fmap = hlo_flops_map(hlo) if hlo else {}

    agg: dict[str, dict] = {}
    for nm, text, dur_ps in _load_xplane_ops(logdir):
        a = agg.setdefault(nm, {"name": nm, "kind": _op_kind(text),
                                "calls": 0, "time_ps": 0,
                                "bytes": _text_bytes(text)})
        a["calls"] += 1
        a["time_ps"] += dur_ps

    # 'while' wraps its body ops (double count) — keep it but mark it
    rows = []
    total_ps = sum(a["time_ps"] for a in agg.values() if a["kind"] != "while")
    for a in agg.values():
        t_s = a["time_ps"] / 1e12
        per_step_calls = a["calls"] / steps
        fl = fmap.get(a["name"], 0) * per_step_calls * steps
        by = a["bytes"] * a["calls"]
        row = {
            "op": a["name"], "kind": a["kind"],
            "calls_per_step": round(per_step_calls, 2),
            "time_ms_per_step": round(t_s / steps * 1e3, 4),
            "time_share": round(a["time_ps"] / total_ps, 4) if total_ps else 0.0,
            "bytes_mb_per_step": round(by / steps / 2**20, 2),
            "gflops_per_step": round(fl / steps / 1e9, 2),
        }
        if t_s > 0:
            row["achieved_gbps"] = round(by / t_s / 1e9, 1)
            row["achieved_tflops"] = round(fl / t_s / 1e12, 2)
            frac = 0.0
            if peak_gbps:
                frac = max(frac, row["achieved_gbps"] / peak_gbps)
            if peak_flops:
                frac = max(frac, row["achieved_tflops"] * 1e12 / peak_flops)
            row["roof_frac"] = round(min(frac, 1.0), 3)
            bound = "latency/other"
            if peak_gbps and row["achieved_gbps"] >= 0.5 * peak_gbps:
                bound = "hbm"
            if peak_flops and row["achieved_tflops"] * 1e12 >= 0.5 * peak_flops:
                bound = "mxu"
            row["bound"] = bound
        rows.append(row)
    rows.sort(key=lambda r: -r["time_ms_per_step"])

    body = [r for r in rows if r["kind"] != "while"]
    comm_ps = sum(r["time_ms_per_step"] for r in body if r["kind"] == "collective")
    step_ms = total_ps / steps / 1e9
    at_half = sum(r["time_share"] for r in body if r.get("roof_frac", 0) >= 0.5)
    at_80 = sum(r["time_share"] for r in body if r.get("roof_frac", 0) >= 0.8)
    return {
        "steps_profiled": steps,
        "device_step_ms": round(step_ms, 3),
        "total_gflops_per_step": round(sum(r["gflops_per_step"] for r in body), 1),
        "total_bytes_gb_per_step": round(
            sum(r["bytes_mb_per_step"] for r in body) / 1024, 3),
        "bytes_note": ("bytes are operand+result sizes per op — an HBM "
                       "upper bound (producer+consumer both count a "
                       "crossing; short-lived VMEM residency not modeled)"),
        "comm_share": round(comm_ps / step_ms, 4) if step_ms else 0.0,
        "time_share_at_half_roof": round(at_half, 4),
        "time_share_at_80pct_roof": round(at_80, 4),
        "peak_tflops": round(peak_flops / 1e12, 1) if peak_flops else None,
        "peak_gbps": peak_gbps,
        "ops": rows[:60],
    }


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="resnet50")
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--out", default="ROOFLINE.json")
    p.add_argument("--peak-gbps", type=float, default=None,
                   help="HBM GB/s (v5e: 819)")
    args = p.parse_args(argv)

    import jax

    import bench as benchmod  # repo-root bench.py: shared model builders

    platform = jax.devices()[0].platform
    trainer, model = benchmod.build_trainer(args.model, platform)
    batch = next(iter(model.data.train_batches(trainer.global_batch, 0, seed=0)))
    from theanompi_tpu.utils.helper_funcs import shard_batch

    placed = shard_batch(trainer.mesh, batch, spec=trainer.batch_spec)
    jax.block_until_ready(placed)
    peak = benchmod.chip_peak_flops()
    gbps = args.peak_gbps or (819.0 if platform == "tpu" else None)
    art = profile_step(trainer, placed, steps=args.steps,
                       peak_flops=peak, peak_gbps=gbps)
    art["model"] = args.model
    art["platform"] = platform
    with open(args.out + ".tmp", "w") as f:
        json.dump(art, f, indent=1)
    os.replace(args.out + ".tmp", args.out)
    print(json.dumps({k: art[k] for k in
                      ("model", "device_step_ms", "total_gflops_per_step",
                       "total_bytes_gb_per_step", "comm_share",
                       "time_share_at_half_roof",
                       "time_share_at_80pct_roof")}))
    for r in art["ops"][:12]:
        print(f"{r['time_ms_per_step']:9.3f} ms  {r['time_share']:6.1%}  "
              f"{r['kind']:11s} {r.get('achieved_gbps', 0):8.0f} GB/s "
              f"{r.get('achieved_tflops', 0):7.2f} TF/s  {r['op'][:48]}")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
