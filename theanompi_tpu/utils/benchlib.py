"""Shared measurement helpers for bench.py and the scaling harness.

Protocol (see bench.py's docstring for the full rationale): jax dispatch is
async, so a timed region must dispatch a chain of steps and synchronize
exactly once at the end — per-step syncs measure round-trip latency (~0.5 s
through this image's tunneled chip), not throughput.  Runs are repeated and
the best trial taken: shared/noisy machines make min-time the capability
estimator.  Keeping the loop here means bench.py and SCALING.json always
measure under the same protocol.
"""

from __future__ import annotations

import time

import numpy as np


def run_trial(trainer, batches, steps: int, feed_mode: str = "placed",
              lr: float = 0.01):
    """One timed trial.  -> (seconds, steps run, input-wait seconds).

    ``feed_mode='placed'``: ``batches`` are device-resident, cycled — times
    the training step itself.  ``'prefetch'``: host batches stream through
    the production Prefetcher (transfer included, overlapped), with the
    dequeue stall timed into the recorder's wait bucket exactly as
    ``BaseTrainer.run`` does.
    """
    rec = trainer.recorder
    rec.time_history.clear()
    if feed_mode == "prefetch":
        from theanompi_tpu.models.data.prefetch import prefetch

        rotation = (batches[i % len(batches)] for i in range(steps))
        feed = prefetch(rotation, mesh=trainer.mesh, depth=4,
                        spec=trainer.batch_spec)
    else:
        feed = [batches[i % len(batches)] for i in range(steps)]
    t0 = time.perf_counter()
    n = 0
    m = None
    it = iter(feed)
    try:
        while True:
            rec.start("wait")  # run()-loop parity: time the dequeue stall
            try:
                b = next(it)
            except StopIteration:
                rec.cancel("wait")
                break
            rec.end("wait")
            m = trainer.train_iter(b, lr=lr)
            n += 1
    finally:
        close = getattr(feed, "close", None)
        if close:
            close()
    float(m["cost"])  # the single sync: drains the dispatched chain
    dt = time.perf_counter() - t0
    return dt, n, float(np.sum(rec.time_history["wait"]))


def best_trial(trainer, batches, steps: int, trials: int,
               feed_mode: str = "placed", lr: float = 0.01):
    """-> ((best seconds, steps, wait seconds), all trial results)."""
    results = [run_trial(trainer, batches, steps, feed_mode, lr=lr)
               for _ in range(trials)]
    return min(results, key=lambda r: r[0] / r[1]), results


def slope_trial(trainer, batches, n_lo: int, n_hi: int,
                feed_mode: str = "placed", lr: float = 0.01):
    """One slope trial -> (sec/step, (dt_lo, dt_hi), wait seconds).

    Every chained trial's wall time carries a constant: the final scalar
    fetch's round trip (up to ~0.5 s through this image's tunnel), which
    inflates ``dt/n`` by ``RTT/n`` — ~10 % at 20 steps of a ~100 ms step.
    Timing a SHORT chain and a LONG chain back-to-back in the same
    throttle window and taking the slope cancels the constant; this is
    the protocol behind BASELINE.md's r4 interleaved-window measurement
    (93.8 ms) that the chain-mode artifact (2484 img/s ≈ 103 ms) sat 10 %
    below.  A trial straddling a throttle transition can produce a
    negative/absurd slope — callers filter (``best_slope``).
    """
    if n_hi <= n_lo:
        raise ValueError(f"slope needs n_hi > n_lo, got {n_lo}..{n_hi}")
    dt_lo, n1, _ = run_trial(trainer, batches, n_lo, feed_mode, lr=lr)
    dt_hi, n2, w_hi = run_trial(trainer, batches, n_hi, feed_mode, lr=lr)
    step_s = (dt_hi - dt_lo) / (n2 - n1)
    # wait seconds of the HI chain only: it covers exactly n_hi steps, so
    # the caller's per-step wait stays comparable with chain-mode artifacts
    return step_s, (dt_lo, dt_hi), w_hi


def best_slope(trainer, batches, n_lo: int, n_hi: int, trials: int,
               feed_mode: str = "placed", lr: float = 0.01):
    """-> ((best sec/step, hi-chain wait seconds), trials, used_fallback).

    Best = the smallest POSITIVE slope (min-time capability estimator);
    non-positive slopes (throttle transitions mid-trial) are excluded
    from "best" but stay in the returned list so the artifact's spread
    shows them.  If every slope is non-positive the chain estimate
    ``dt_hi/n_hi`` of the fastest trial substitutes — flagged via
    ``used_fallback`` so the artifact cannot pass an RTT-inflated chain
    number off as a slope measurement.
    """
    results = [slope_trial(trainer, batches, n_lo, n_hi, feed_mode, lr=lr)
               for _ in range(trials)]
    positive = [r for r in results if r[0] > 0]
    if positive:
        best = min(positive, key=lambda r: r[0])
        return (best[0], best[2]), results, False
    fallback = min(results, key=lambda r: r[1][1])
    return (fallback[1][1] / n_hi, fallback[2]), results, True
