"""Shared measurement helpers for bench.py and the scaling harness.

Protocol (see bench.py's docstring for the full rationale): jax dispatch is
async, so a timed region must dispatch a chain of steps and synchronize
exactly once at the end — per-step syncs measure round-trip latency (~0.5 s
through this image's tunneled chip), not throughput.  Runs are repeated and
the best trial taken: shared/noisy machines make min-time the capability
estimator.  Keeping the loop here means bench.py and SCALING.json always
measure under the same protocol.
"""

from __future__ import annotations

import time

import numpy as np


def run_trial(trainer, batches, steps: int, feed_mode: str = "placed",
              lr: float = 0.01):
    """One timed trial.  -> (seconds, steps run, input-wait seconds).

    ``feed_mode='placed'``: ``batches`` are device-resident, cycled — times
    the training step itself.  ``'prefetch'``: host batches stream through
    the production Prefetcher (transfer included, overlapped), with the
    dequeue stall timed into the recorder's wait bucket exactly as
    ``BaseTrainer.run`` does.
    """
    rec = trainer.recorder
    rec.time_history.clear()
    if feed_mode == "prefetch":
        from theanompi_tpu.models.data.prefetch import prefetch

        rotation = (batches[i % len(batches)] for i in range(steps))
        feed = prefetch(rotation, mesh=trainer.mesh, depth=4,
                        spec=trainer.batch_spec)
    else:
        feed = [batches[i % len(batches)] for i in range(steps)]
    t0 = time.perf_counter()
    n = 0
    m = None
    it = iter(feed)
    try:
        while True:
            rec.start("wait")  # run()-loop parity: time the dequeue stall
            try:
                b = next(it)
            except StopIteration:
                rec.cancel("wait")
                break
            rec.end("wait")
            m = trainer.train_iter(b, lr=lr)
            n += 1
    finally:
        close = getattr(feed, "close", None)
        if close:
            close()
    float(m["cost"])  # the single sync: drains the dispatched chain
    dt = time.perf_counter() - t0
    return dt, n, float(np.sum(rec.time_history["wait"]))


def best_trial(trainer, batches, steps: int, trials: int,
               feed_mode: str = "placed", lr: float = 0.01):
    """-> ((best seconds, steps, wait seconds), all trial results)."""
    results = [run_trial(trainer, batches, steps, feed_mode, lr=lr)
               for _ in range(trials)]
    return min(results, key=lambda r: r[0] / r[1]), results
