"""Cross-replica divergence check (SURVEY.md §5 race-detection row).

The reference had no race detection; under SPMD the one real invariant is
that *replicated* values stay bit-identical across their device copies —
BSP params after the fused all-reduce, EASGD's center, batch-norm state
under sync-BN.  A divergence means a non-deterministic op, a wrong
``grad_reduce_axes``, or an exchange bug (exactly the class the round-1
Megatron-gradient bug belonged to), and shard_map's ``check_rep=False``
hides it silently.

The check is host-side and collective-free: every device copy of a
replicated leaf is an addressable shard covering the same index, so the
copies can be fetched and compared directly.  Cost is a device→host pull
of the tree — a debug tool, not a per-step assertion; wire it at epoch
boundaries via ``BaseTrainer.check_divergence()``.
"""

from __future__ import annotations

import jax
import numpy as np


def replica_divergence(tree) -> float:
    """Max |difference| between same-index device copies across the tree.

    Leaves without multiple same-index addressable shards (fully sharded
    arrays, scalars on one device) contribute nothing.  0.0 means every
    replicated copy is bit-identical.
    """
    worst = 0.0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None or len(shards) < 2:
            continue
        by_index: dict = {}
        for s in shards:
            key = tuple(
                (sl.start, sl.stop, sl.step) for sl in s.index
            ) if s.index else ()
            by_index.setdefault(key, []).append(s)
        for copies in by_index.values():
            if len(copies) < 2:
                continue
            arrs = [np.asarray(s.data).astype(np.float64) for s in copies]
            ref_nan = np.isnan(arrs[0])
            for a in arrs[1:]:
                if (np.isnan(a) != ref_nan).any():
                    # a NaN on one copy but not another IS divergence (the
                    # prime symptom of the bugs this tool exists to catch);
                    # naive max() would silently drop the NaN comparison
                    return float("inf")
            # max PAIRWISE spread via elementwise min/max over all copies
            # (comparing only against copies[0] under-reports by up to 2x);
            # matching NaN/inf positions are equal, mixed inf-vs-finite
            # yields inf spread
            stack = np.where(np.isnan(arrs), 0.0, np.stack(arrs))
            hi, lo = stack.max(axis=0), stack.min(axis=0)
            # subtract only where copies differ: matching infs would warn
            # (inf - inf) even though the result is masked
            spread = np.zeros_like(hi)
            np.subtract(hi, lo, out=spread, where=hi != lo)
            worst = max(worst, float(spread.max()) if spread.size else 0.0)
    return worst


def assert_replicas_in_sync(tree, atol: float = 0.0, what: str = "tree") -> float:
    """Raise if replicated copies diverge beyond ``atol``; -> measured max."""
    d = replica_divergence(tree)
    if d > atol:
        raise AssertionError(
            f"replica divergence in {what}: max |delta| = {d} > {atol} — "
            "a replicated value differs between device copies (wrong "
            "reduce axes, non-determinism, or an exchange bug)"
        )
    return d
