"""Utilities: recorder, checkpointing, helper functions.

Reference (unverified — SURVEY.md §2.1): ``theanompi/lib/recorder.py`` and
``theanompi/lib/helper_funcs.py``.
"""

from theanompi_tpu.utils.recorder import Recorder

__all__ = ["Recorder"]
