"""Training recorder: per-iteration wall-clock splits + metric histories.

Reference (unverified — SURVEY.md §2.1/§5): ``theanompi/lib/recorder.py`` —
``Recorder.start/end`` wall-clock segments (calc / comm / wait) threaded
through ``train_iter``/``exchange``, train cost+error printed every N
iterations, epoch validation stats, ``.npy`` histories dumped to a record
dir.  The API is preserved; the TPU twist is honesty under async dispatch:
jax returns control before the device finishes, so ``end()`` accepts a
``fence`` array to ``block_until_ready`` — without it the calc/comm split is
meaningless (SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict

import numpy as np

SEGMENTS = ("wait", "calc", "comm")


class Recorder:
    def __init__(self, print_freq: int = 40, save_dir: str | None = None,
                 rank: int = 0, verbose: bool = True, telemetry=None):
        self.print_freq = print_freq
        self.save_dir = save_dir
        self.verbose = verbose and rank == 0
        # optional telemetry sink: each closed segment is also emitted as a
        # structured span (same start/duration the histories record, so the
        # Perfetto view and the .npy splits are one set of numbers)
        self.telemetry = telemetry
        self._t0: dict[str, float] = {}
        self._iter_times: dict[str, float] = defaultdict(float)
        self.time_history: dict[str, list] = defaultdict(list)
        self.train_history: dict[str, list] = defaultdict(list)
        self.val_history: dict[str, list] = defaultdict(list)
        self._train_accum: dict[str, list] = defaultdict(list)
        self.epoch_start_time: float | None = None

    # -- wall-clock segments ------------------------------------------------
    def start(self, what: str = "calc") -> None:
        self._t0[what] = time.perf_counter()

    def end(self, what: str = "calc", fence=None) -> None:
        """Close segment ``what``; pass a jax array as ``fence`` to block on
        device completion so the split reflects device time, not dispatch."""
        if fence is not None:
            import jax

            # no exception guard: an async device error surfacing at the
            # fence (the one deliberate sync point) must propagate here, not
            # at some arbitrary later sync with a misleading stack
            jax.block_until_ready(fence)
        t0 = self._t0.pop(what, None)
        if t0 is None:
            raise RuntimeError(
                f"Recorder.end({what!r}): segment was never started "
                f"(open segments: {sorted(self._t0) or 'none'}); "
                f"use cancel() to abandon a segment"
            )
        dur = time.perf_counter() - t0
        self._iter_times[what] += dur
        if self.telemetry is not None:
            self.telemetry.emit_span(f"recorder.{what}", t0, dur)

    def cancel(self, what: str) -> None:
        """Abandon an open segment without recording it (e.g. the wait
        opened before a ``next()`` that raised StopIteration)."""
        self._t0.pop(what, None)

    def end_iteration(self) -> None:
        for seg in SEGMENTS:
            self.time_history[seg].append(self._iter_times.get(seg, 0.0))
        self._iter_times.clear()

    # -- metrics ------------------------------------------------------------
    def train_metrics(self, **metrics) -> None:
        """Accumulate per-iteration metrics.

        Values may be device arrays; conversion to host floats is deferred to
        the print boundary so per-iteration recording never forces a device
        sync (which would serialize the dispatch pipeline on TPU).
        """
        for k, v in metrics.items():
            self._train_accum[k].append(v)

    def print_train_info(self, count: int) -> None:
        """Every ``print_freq`` iterations: averaged metrics + time split."""
        if count % self.print_freq != 0 or not self._train_accum:
            return
        # np.asarray(...).mean(): metrics may be per-worker vectors (the
        # async rules report without a cross-worker collective in the step)
        means = {
            k: float(np.mean([np.asarray(x).mean() for x in v]))
            for k, v in self._train_accum.items()
        }
        for k, v in means.items():
            self.train_history[k].append(v)
        self.train_history["iter"].append(count)
        if self.verbose:
            metric_s = " ".join(f"{k} {v:.4f}" for k, v in means.items())
            n = min(self.print_freq, len(self.time_history["calc"]) or 1)
            times = {
                seg: float(np.sum(self.time_history[seg][-n:]))
                for seg in SEGMENTS
            }
            time_s = " ".join(f"{s} {t:.3f}s" for s, t in times.items())
            print(f"iter {count}: {metric_s} | {time_s}", flush=True)
        self._train_accum.clear()

    def val_metrics(self, epoch: int, **metrics) -> None:
        self.val_history["epoch"].append(epoch)
        for k, v in metrics.items():
            self.val_history[k].append(float(v))
        if self.telemetry is not None:
            self.telemetry.instant(
                "val_metrics", epoch=epoch,
                **{k: float(v) for k, v in metrics.items()})
        if self.verbose:
            metric_s = " ".join(f"val_{k} {float(v):.4f}" for k, v in metrics.items())
            dur = (
                f" ({time.perf_counter() - self.epoch_start_time:.1f}s)"
                if self.epoch_start_time
                else ""
            )
            print(f"epoch {epoch}: {metric_s}{dur}", flush=True)

    def start_epoch(self) -> None:
        self.epoch_start_time = time.perf_counter()

    def latest_val(self, key: str = "cost"):
        vals = self.val_history.get(key)
        return vals[-1] if vals else None

    # -- persistence (reference dumped .npy histories into record/) ---------
    def history_snapshot(self) -> dict:
        """Point-in-time copy of the three histories as plain lists.

        Cheap (list copies on the calling thread), so the async checkpoint
        writer can serialize it off-thread without racing later iterations
        mutating the live defaultdicts (ISSUE 3 — the boundary pays neither
        the .npy nor the .npz write).
        """
        return {
            "time": {k: list(v) for k, v in self.time_history.items()},
            "train": {k: list(v) for k, v in self.train_history.items()},
            "val": {k: list(v) for k, v in self.val_history.items()},
        }

    def save(self, path: str | None = None) -> None:
        path = path or self.save_dir
        if path is None:
            return
        write_history_snapshot(self.history_snapshot(), path)

    def load(self, path: str | None = None) -> None:
        path = path or self.save_dir
        if path is None:
            return
        for name, hist in (
            ("time", self.time_history),
            ("train", self.train_history),
            ("val", self.val_history),
        ):
            p = os.path.join(path, f"{name}_history.npy")
            if os.path.exists(p):
                loaded = np.load(p, allow_pickle=True).item()
                hist.clear()
                # tolist(), not list(): numpy scalars (np.int64 epochs)
                # must not leak into the histories — a later save() would
                # fail json-serializing summary.json (resume, then train
                # more, then save — the supervisor's bread and butter)
                hist.update({k: np.asarray(v).tolist()
                             for k, v in loaded.items()})


def write_history_snapshot(snapshot: dict, path: str) -> None:
    """Serialize a :meth:`Recorder.history_snapshot` to ``path`` — the
    ``*_history.npy`` files + ``summary.json`` :meth:`Recorder.load` reads.
    Split out of :meth:`Recorder.save` so the async checkpoint writer can
    run it on the background thread against an immutable snapshot."""
    os.makedirs(path, exist_ok=True)
    for name in ("time", "train", "val"):
        hist = snapshot.get(name, {})
        np.save(
            os.path.join(path, f"{name}_history.npy"),
            {k: np.asarray(v) for k, v in hist.items()},
            allow_pickle=True,
        )
    spath = os.path.join(path, "summary.json")
    with open(spath + ".tmp", "w") as f:
        json.dump(
            {
                "iters": len(snapshot.get("time", {}).get("calc", ())),
                "last_val": {
                    k: v[-1]
                    for k, v in snapshot.get("val", {}).items() if v
                },
            },
            f,
        )
    os.replace(spath + ".tmp", spath)
